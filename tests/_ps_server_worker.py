"""PSServer subprocess for the at-scale PS bench: one server process
hosting one SparseTable shard; prints its endpoint and serves until
killed."""
import os
import sys
import time


def main():
    from paddle_tpu.ps.service import PSServer
    from paddle_tpu.ps.table import SparseTable

    dim = int(os.environ.get("PS_DIM", "16"))
    srv = PSServer({0: SparseTable(dim=dim, init_range=0.01, seed=1)})
    srv.start()
    print(f"ENDPOINT {srv.endpoint}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
