"""RNN sequence_length semantics (reference rnn.py mask_fn / LoD-aware
dynamic_rnn): outputs past a sequence's length are zero, the carry
freezes at the last valid step, and the backward direction of a biLSTM
starts at position len-1 — so logits are invariant to trailing padding."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn

import pytest

pytestmark = pytest.mark.slow


def _run(layer, x, lens):
    out, (h, c) = layer(paddle.to_tensor(x),
                        sequence_length=paddle.to_tensor(lens))
    return out.numpy(), h.numpy(), c.numpy()


def test_lstm_padding_invariance_bidirectional():
    paddle.seed(0)
    lstm = nn.LSTM(4, 8, direction="bidirectional")
    lstm.eval()
    rng = np.random.RandomState(0)
    base = rng.randn(2, 5, 4).astype("float32")
    lens = np.array([5, 3], np.int64)

    pad8 = np.zeros((2, 8, 4), np.float32)
    pad8[:, :5] = base
    out5, h5, c5 = _run(lstm, base, lens)
    out8, h8, c8 = _run(lstm, pad8, lens)

    # valid region identical regardless of padding amount
    np.testing.assert_allclose(out8[0, :5], out5[0, :5], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(out8[1, :3], out5[1, :3], rtol=1e-5,
                               atol=1e-6)
    # outputs past length are zeros
    assert np.all(out8[1, 3:] == 0) and np.all(out8[0, 5:] == 0)
    # final states identical
    np.testing.assert_allclose(h8, h5, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(c8, c5, rtol=1e-5, atol=1e-6)


def test_forward_lstm_final_state_at_length():
    paddle.seed(0)
    lstm = nn.LSTM(3, 6)
    lstm.eval()
    rng = np.random.RandomState(1)
    x = rng.randn(1, 7, 3).astype("float32")
    # run full 4 steps on the truncated sequence vs lengths=4 on padded
    out_trunc, (h_t, _) = lstm(paddle.to_tensor(x[:, :4]))
    out_len, h_l, _ = _run(lstm, x, np.array([4], np.int64))
    np.testing.assert_allclose(h_l, h_t.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out_len[:, :4], out_trunc.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_gru_and_simple_rnn_lengths():
    paddle.seed(0)
    for cls in (nn.GRU, nn.SimpleRNN):
        layer = cls(3, 5, direction="bidirectional")
        layer.eval()
        rng = np.random.RandomState(2)
        base = rng.randn(2, 4, 3).astype("float32")
        lens = np.array([4, 2], np.int64)
        pad = np.zeros((2, 6, 3), np.float32)
        pad[:, :4] = base
        out4, h4, *_ = _run_any(layer, base, lens)
        out6, h6, *_ = _run_any(layer, pad, lens)
        np.testing.assert_allclose(out6[1, :2], out4[1, :2], rtol=1e-5,
                                   atol=1e-6)
        assert np.all(out6[1, 2:] == 0)
        np.testing.assert_allclose(h6, h4, rtol=1e-5, atol=1e-6)


def _run_any(layer, x, lens):
    out, st = layer(paddle.to_tensor(x),
                    sequence_length=paddle.to_tensor(lens))
    if isinstance(st, tuple):
        return (out.numpy(),) + tuple(s.numpy() for s in st)
    return out.numpy(), st.numpy()


def test_sentiment_logits_padding_invariant():
    from paddle_tpu.models.sentiment import SentimentLSTM

    paddle.seed(0)
    model = SentimentLSTM(vocab_size=30, embed_dim=8, hidden_dim=8,
                          dropout=0.0)
    model.eval()
    ids5 = np.array([[3, 9, 4, 7, 1]], np.int64)
    ids12 = np.zeros((1, 12), np.int64)
    ids12[0, :5] = ids5
    l5 = model(paddle.to_tensor(ids5)).numpy()
    l12 = model(paddle.to_tensor(ids12)).numpy()
    np.testing.assert_allclose(l12, l5, rtol=1e-5, atol=1e-6)
