"""Numeric gradient checks (reference OpTest check_grad, SURVEY §4.1)
for the round-5 kernels and contrib ops: fused linear+cross-entropy
(custom_vjp vs central differences, interpret mode), flash-ring
attention (custom_vjp through the shard_map ring), and the contrib
dense+lengths ops (match_matrix_tensor, var_conv_2d, tree_conv,
rank_attention, bilateral_slice, sequence_topk_avg_pooling).
Small shapes — finite differences are O(numel) forward passes."""
import functools

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu  # noqa: F401
import paddle_tpu.framework.bringup as bringup
from paddle_tpu.framework.tensor import Tensor

pytestmark = pytest.mark.slow


from tests.op_test import check_grad as _check
from tests.op_test import probe_check_grad as _probe_check


@pytest.fixture
def interp(monkeypatch):
    from jax.experimental import pallas as pl

    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(pl.pallas_call, interpret=True))
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    yield


def test_fused_xent_numeric_grads(interp):
    from paddle_tpu.ops.pallas.fused_xent import _fused_xent_core

    rng = np.random.RandomState(0)
    # tiny but eligible: rows pad to 256 upstream, so call the core
    # directly at an exact block shape
    h0 = rng.randn(256, 128).astype(np.float32) * 0.3
    w = jnp.asarray(rng.randn(128, 128) * 0.3)   # vocab 128
    b = jnp.asarray(rng.randn(128) * 0.1)
    lab = jnp.asarray(rng.randint(0, 128, 256), jnp.int32)

    _probe_check(lambda h: _fused_xent_core(h, w, b, lab, -100), h0,
                 probes=[(0, 0), (13, 64), (200, 127), (255, 1)])

    def loss_w(wm):
        return _fused_xent_core(jnp.asarray(h0), wm, b, lab, -100)

    _probe_check(loss_w, np.asarray(w),
                 probes=[(7, 0), (40, 100), (127, 64)])


def test_flash_ring_numeric_grads(interp, monkeypatch):
    import paddle_tpu.parallel.ring as ring_mod
    from paddle_tpu.parallel import create_mesh, set_mesh, ring_attention
    from paddle_tpu.parallel.mesh import _global_mesh

    monkeypatch.setattr(ring_mod, "_SHARD_MAP_CHECK_VMA", [False])
    prev = _global_mesh[0]        # BEFORE create_mesh (it sets the global)
    mesh = create_mesh({"sp": 4})
    set_mesh(mesh)
    try:
        rng = np.random.RandomState(1)
        q0 = rng.randn(1, 512, 1, 64).astype(np.float32) * 0.4
        k = jnp.asarray(rng.randn(1, 512, 1, 64) * 0.4, jnp.float32)
        v = jnp.asarray(rng.randn(1, 512, 1, 64) * 0.4, jnp.float32)
        wsum = jnp.asarray(rng.randn(1, 512, 1, 64), jnp.float32)

        def loss(q):
            return jnp.sum(wsum * ring_attention(
                q, k, v, mesh=mesh, is_causal=True))

        # numeric over a small probe region (full tensor = 32k fwds)
        _probe_check(loss, q0, [(0, 5, 0, 3), (0, 100, 0, 60),
                                (0, 300, 0, 0), (0, 511, 0, 63)])
    finally:
        _global_mesh[0] = prev


def test_contrib_ops_numeric_grads():
    from paddle_tpu import contrib

    rng = np.random.RandomState(2)

    # match_matrix_tensor: grad wrt x
    x0 = rng.randn(1, 3, 4).astype(np.float32) * 0.5
    y = Tensor(jnp.asarray(rng.randn(1, 2, 4) * 0.5, jnp.float32))
    w = Tensor(jnp.asarray(rng.randn(4, 2, 4) * 0.5, jnp.float32))
    xl = Tensor(np.array([3], np.int64))
    yl = Tensor(np.array([2], np.int64))

    def mm_loss(x):
        out, _ = contrib.match_matrix_tensor(
            Tensor(x), y, 2, x_lengths=xl, y_lengths=yl, weight=w)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(mm_loss, x0)

    # var_conv_2d: grad wrt input
    xi0 = rng.randn(1, 1, 4, 4).astype(np.float32) * 0.5
    cw = Tensor(jnp.asarray(rng.randn(2, 9) * 0.3, jnp.float32))
    row = Tensor(np.array([4], np.int64))
    col = Tensor(np.array([3], np.int64))

    def conv_loss(xi):
        out, _, _ = contrib.var_conv_2d(
            Tensor(xi), row, col, 1, 2, [3, 3], weight=cw)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(conv_loss, xi0)

    # tree_conv: grad wrt node features
    nv0 = rng.randn(1, 3, 4).astype(np.float32) * 0.5
    edges = np.array([[[1, 2], [1, 3], [0, 0]]], np.int32)
    tw = Tensor(jnp.asarray(rng.randn(4, 3, 5, 2) * 0.3, jnp.float32))

    def tree_loss(nv):
        out = contrib.tree_conv(Tensor(nv), Tensor(edges), 5, 2,
                                act=None, weight=tw, bias=None)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(tree_loss, nv0)

    # rank_attention: grad wrt input
    ri0 = rng.randn(3, 2).astype(np.float32) * 0.5
    ro = Tensor(np.array([[1, 1, 0, 2, 1, 0, 0],
                          [2, 1, 0, 2, 1, 3, 2],
                          [1, 2, 2, 0, 0, 0, 0]], np.int32))
    rp = Tensor(jnp.asarray(rng.randn(2 * 9, 4) * 0.3, jnp.float32))

    def rank_loss(ri):
        out = contrib.rank_attention(Tensor(ri), ro, [2 * 9, 4],
                                     max_rank=3, rank_param=rp)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(rank_loss, ri0)

    # bilateral_slice: grad wrt grid (smooth in grid)
    g0 = rng.randn(1, 2, 2, 2, 2).astype(np.float32) * 0.5
    xs = Tensor(jnp.asarray(rng.rand(1, 1, 3, 3), jnp.float32))
    guide = Tensor(jnp.asarray(rng.rand(1, 3, 3) * 0.8 + 0.1,
                               jnp.float32))

    def bs_loss(g):
        out = contrib.bilateral_slice(xs, guide, Tensor(g), True)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(bs_loss, g0)

    # sequence_topk_avg_pooling: grad wrt input (top-k selection is
    # locally constant; keep values well-separated)
    ti0 = (np.arange(16).reshape(1, 1, 4, 4).astype(np.float32) / 4.0
           + rng.rand(1, 1, 4, 4).astype(np.float32) * 0.01)
    trow = Tensor(np.array([3], np.int64))
    tcol = Tensor(np.array([4], np.int64))

    def topk_loss(ti):
        out = contrib.sequence_topk_avg_pooling(Tensor(ti), trow, tcol,
                                                [1, 2], 1)
        return jnp.sum(jnp.asarray(out.value) ** 2)

    _check(topk_loss, ti0)


def test_sharded_fused_xent_numeric_grads(interp, monkeypatch):
    """The shard_map'd multi-device fused-xent path (sum-form vjp +
    psum transpose): gradients at probe points vs central differences."""
    import paddle_tpu.parallel.ring as ring_mod
    from paddle_tpu.ops.pallas.fused_xent import _sharded_fused
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.parallel.mesh import _global_mesh

    monkeypatch.setattr(ring_mod, "_SHARD_MAP_CHECK_VMA", [False])
    prev = _global_mesh[0]
    import jax as _jax
    mesh = create_mesh({"dp": 2}, devices=_jax.devices()[:2])
    try:
        rng = np.random.RandomState(3)
        h0 = rng.randn(512, 128).astype(np.float32) * 0.3   # 256/shard
        w = jnp.asarray(rng.randn(128, 128) * 0.3)
        b = jnp.asarray(rng.randn(128) * 0.1)
        lab = jnp.asarray(rng.randint(0, 128, 512), jnp.int32)

        def loss_h(h):
            return _sharded_fused(h, w, b, lab, mesh, ("dp",), -100)

        _probe_check(loss_h, h0, probes=[(0, 0), (255, 64), (256, 1),
                                         (511, 127)])

        def loss_w(wm):
            return _sharded_fused(jnp.asarray(h0), wm, b, lab, mesh,
                                  ("dp",), -100)

        # W is replicated across shards: its cotangent is the psum of
        # per-shard contributions — the transpose this check pins
        _probe_check(loss_w, np.asarray(w), probes=[(7, 0), (100, 64)])
    finally:
        _global_mesh[0] = prev
