"""Static-graph auto_mixed_precision pass: knob matrix, master weights,
cast bookkeeping, feed path, fp16 loss scaling, amp.decorate satellites.

Contract being pinned:
- amp-on loss tracks the f32 loss within tolerance (roundoff, not drift)
- PADDLE_AMP=0 restores bitwise-f32 behavior whatever the strategy says
- parameters stay f32 master weights (bitwise untouched when amp only
  wraps compute), optimizer updates run f32
- the compile cache distinguishes amp-on/off (no stale executables)
- __rng_slot keeps random draws stable while casts shift op indices
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import passes as passes_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_KNOBS = ("fuse_elewise_add_act_ops", "memory_optimize",
             "enable_inplace", "constant_folding", "cse")


def _strategy(amp=None, level="O1", others=False):
    bs = static.BuildStrategy()
    for k in ALL_KNOBS:
        setattr(bs, k, bool(others))
    if amp:
        bs.amp = True
        bs.amp_dtype = amp
        bs.amp_level = level
    else:
        bs.amp = False
    return bs


def _train_program(seed=1234):
    """Small MLP + bert-ish block: white mul ops, gray adds, a black
    softmax-xent loss, SGD update ops past the backward boundary."""
    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = seed
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 8])
        label = static.data("label", [-1, 1], dtype="int64")
        h = static.nn.fc(x, 16, act="relu")
        h = static.nn.fc(h, 8)
        logits = static.nn.fc(h, 4)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label))
        static.SGD(0.05).minimize(loss)
    return main, startup, loss


def _feed(n=8):
    rng = np.random.RandomState(0)
    return {"x": rng.randn(n, 8).astype(np.float32),
            "label": rng.randint(0, 4, (n, 1)).astype(np.int64)}


def _run_leg(strategy, steps=3):
    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup, loss = _train_program()
        exe = static.Executor()
        exe.run(startup)
        cp = static.CompiledProgram(main, build_strategy=strategy)
        feed = _feed()
        out = [exe.run(cp, feed=feed, fetch_list=[loss])[0]
               for _ in range(steps)]
        return [float(np.ravel(v)[0]) for v in out], dict(exe.counters)


F32 = None


def _f32_leg():
    global F32
    if F32 is None:
        F32 = _run_leg(_strategy())
    return F32


# ---------------------------------------------------------------------------
# rewrite structure
# ---------------------------------------------------------------------------
def test_amp_inserts_casts_and_lowers_white_ops():
    main, _, loss = _train_program()
    n_ops = len(main.global_block.ops)
    opt, report = passes_mod.apply_passes(
        main, ["x", "label"], [loss.name], _strategy(amp="bfloat16"))
    ran = [s.name for s in report.stats]
    assert ran[0] == "auto_mixed_precision"
    assert report.amp["amp_casts_inserted"] > 0
    assert report.amp["amp_ops_lowprec"] >= 3          # the three muls
    assert report.amp["amp_master_params"] >= 3        # their f32 weights
    assert report.amp["amp_lowprec_feeds"] == 1        # x, not label
    types = [op.type for op in opt.global_block.ops]
    assert "cast" in types
    # user program untouched
    assert len(main.global_block.ops) == n_ops
    assert "cast" not in [op.type for op in main.global_block.ops]
    # the float feed flipped low in the OPTIMIZED program only
    assert opt.global_block.vars["x"].dtype == "bfloat16"
    assert main.global_block.vars["x"].dtype == "float32"
    # optimizer region untouched: every param stays an f32 master
    pnames = [p.name for p in main.all_parameters()]
    assert pnames
    for n in pnames:
        assert opt.global_block.vars[n].dtype == "float32", n


def test_amp_black_ops_pinned_f32():
    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 8])
            h = static.nn.fc(x, 4)
            out = static.softmax(h)
        opt, _ = passes_mod.apply_passes(
            main, ["x"], [out.name], _strategy(amp="bfloat16"))
        blk = opt.global_block
        (sm,) = [op for op in blk.ops if op.type == "softmax"]
        # softmax input was cast back up; its (fetched) output stays f32
        assert blk.vars[sm.inputs["X"][0]].dtype == "float32"
        assert blk.vars[out.name].dtype == "float32"
        exe = static.Executor()
        exe.run(startup)
        got = exe.run(static.CompiledProgram(
            main, build_strategy=_strategy(amp="bfloat16")),
            feed={"x": np.random.RandomState(0).randn(4, 8).astype(
                np.float32)}, fetch_list=[out])[0]
        assert got.dtype == np.float32
        np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-3)


def test_amp_cast_dedup_and_roundtrip_elision():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 8])
        # h is fetched (protected): produced low, cast back up to f32
        h = static.nn.fc(x, 8)
        # both consumers of h cast it down again -> exact round trip,
        # and the two identical casts dedup to one
        a = static.nn.fc(h, 4)
        b = static.nn.fc(h, 4)
        out = static.elementwise_add(a, b)
    opt, report = passes_mod.apply_passes(
        main, ["x"], [h.name, out.name], _strategy(amp="bfloat16"))
    assert report.amp["amp_casts_elided"] >= 1
    # no cast op re-lowers h: its consumers read the low alias directly
    down_casts = [op for op in opt.global_block.ops
                  if op.type == "cast" and op.inputs["X"] == [h.name]]
    assert not down_casts, [o.to_dict() for o in down_casts]


# ---------------------------------------------------------------------------
# loss parity matrix: O1/O2 x bf16/fp16 x other passes on/off
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("level", ["O1", "O2"])
@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
@pytest.mark.parametrize("others", [False, True])
def test_amp_matrix_loss_parity(level, dtype, others):
    base, _ = _f32_leg()
    losses, counters = _run_leg(_strategy(amp=dtype, level=level,
                                          others=others))
    assert counters["amp_casts_inserted"] > 0
    assert counters["amp_ops_lowprec"] > 0
    # first step is pure forward roundoff; later steps compound updates
    assert abs(losses[0] - base[0]) / abs(base[0]) < 1e-2, (losses, base)
    for got, want in zip(losses, base):
        assert abs(got - want) / abs(want) < 5e-2, (losses, base)
    if dtype == "float16":
        assert counters.get("amp_loss_scaled", 0) >= 1


def test_amp_env_zero_restores_bitwise_f32(monkeypatch):
    base, _ = _f32_leg()
    monkeypatch.setenv("PADDLE_AMP", "0")
    losses, counters = _run_leg(_strategy(amp="bfloat16"))
    assert losses == base
    assert counters.get("amp_casts_inserted", 0) == 0


def test_amp_env_force_enables(monkeypatch):
    monkeypatch.setenv("PADDLE_AMP", "bf16")
    main, _, loss = _train_program()
    opt, report = passes_mod.apply_passes(
        main, ["x", "label"], [loss.name], _strategy())  # amp=False
    assert report.amp.get("amp_casts_inserted", 0) > 0
    monkeypatch.setenv("PADDLE_AMP", "nonsense")
    with pytest.raises(ValueError):
        passes_mod.resolve_amp(None)


# ---------------------------------------------------------------------------
# executor integration
# ---------------------------------------------------------------------------
def test_amp_compile_cache_distinguishes_modes():
    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup, loss = _train_program()
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        exe.run(static.CompiledProgram(main, _strategy()),
                feed=feed, fetch_list=[loss])
        assert exe.counters["compile_cache_misses"] == 1
        exe.run(static.CompiledProgram(main, _strategy(amp="bfloat16")),
                feed=feed, fetch_list=[loss])
        assert exe.counters["compile_cache_misses"] == 2, \
            "amp-on hit the f32 executable"
        exe.run(static.CompiledProgram(main, _strategy(amp="bfloat16")),
                feed=feed, fetch_list=[loss])
        assert exe.counters["compile_cache_misses"] == 2
        assert exe.counters["compile_cache_hits"] >= 1


def test_amp_feed_cast_halves_h2d_bytes():
    _, off = _run_leg(_strategy())
    _, on = _run_leg(_strategy(amp="bfloat16"))
    assert on["h2d_bytes"] < off["h2d_bytes"], (on, off)
    # state upload identical (f32 masters both legs): the drop is feeds
    assert on.get("state_h2d_bytes", 0) == off.get("state_h2d_bytes", 0)


def test_amp_master_weights_bitwise_invariant():
    """Inference-style run: amp wraps only compute, so the f32 params in
    the scope must come back bitwise identical."""
    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup = static.Program(), static.Program()
        main.random_seed = startup.random_seed = 3
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 8])
            out = static.reduce_mean(static.nn.fc(x, 4))
        exe = static.Executor()
        exe.run(startup)
        pnames = [p.name for p in main.all_parameters()]
        assert pnames
        before = {n: np.asarray(scope.find_var(n)).tobytes()
                  for n in pnames}
        exe.run(static.CompiledProgram(main, _strategy(amp="bfloat16")),
                feed={"x": np.ones((2, 8), np.float32)},
                fetch_list=[out])
        for n, b in before.items():
            arr = np.asarray(scope.find_var(n))
            assert arr.dtype == np.float32
            assert arr.tobytes() == b, f"{n} mutated by amp compute"
        assert exe.counters["amp_master_params"] >= 1


def test_amp_rng_stable_under_dce():
    """Casts shift op indices and DCE removes ops; __rng_slot must keep
    the dropout mask identical between the two amp legs."""
    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup = static.Program(), static.Program()
        main.random_seed = startup.random_seed = 77
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 8])
            static.scale(x, scale=2.0)  # dead op BEFORE the dropout
            h = static.dropout(static.nn.fc(x, 8), dropout_prob=0.5)
            out = static.reduce_mean(h)
        static.Executor().run(startup)
        feed = {"x": np.ones((4, 8), np.float32)}
        legs = {}
        for mode, others in (("plain", False), ("dce", True)):
            # fresh executor per leg: the RNG folds in the step counter
            exe = static.Executor()
            legs[mode] = exe.run(static.CompiledProgram(
                main, build_strategy=_strategy(amp="bfloat16",
                                               others=others)),
                feed=feed, fetch_list=[out])[0]
        assert legs["plain"].tobytes() == legs["dce"].tobytes(), \
            "amp + DCE shifted a dropout draw"


def test_amp_never_casts_integer_outputs():
    """Review regression: arg_max produces int64 from a float input; the
    bookkeeping must not stamp it float, or a downstream gather gets
    bfloat16 indices and the trace crashes."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 8])
        z = static.data("z", [-1, 4])
        y = static.nn.fc(x, 8)                  # white: bf16 producer
        idx = static.argmax(z, axis=1)          # int64 from float input
        out = static.reduce_mean(static.gather(y, idx))
    opt, _ = passes_mod.apply_passes(
        main, ["x", "z"], [out.name], _strategy(amp="bfloat16"))
    for op in opt.global_block.ops:
        if op.type == "cast":
            src = op.inputs["X"][0]
            assert idx.name not in src, "integer index var was cast"
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(4, 8).astype(np.float32),
            "z": rng.randn(4, 4).astype(np.float32)}
    scope = static.Scope()
    with static.scope_guard(scope):
        # no params needed beyond fc's: run the whole thing end to end
        main2, startup2 = static.Program(), static.Program()
        with static.program_guard(main2, startup2):
            x = static.data("x", [-1, 8])
            z = static.data("z", [-1, 4])
            y = static.nn.fc(x, 8)
            idx = static.argmax(z, axis=1)
            out = static.reduce_mean(static.gather(y, idx))
        exe = static.Executor()
        exe.run(startup2)
        got = exe.run(static.CompiledProgram(
            main2, build_strategy=_strategy(amp="bfloat16")),
            feed=feed, fetch_list=[out])[0]
        assert np.isfinite(got).all()


def test_amp_feed_into_black_op_stays_f32():
    """Review regression: a feed consumed by a pinned op must not be
    quantized host-side — the black-list contract holds at inputs."""
    from paddle_tpu.static.passes import amp_feed_dtypes

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 8])        # white consumer only
        w = static.data("w", [-1, 8])        # feeds softmax directly
        h = static.nn.fc(x, 8)
        out = static.reduce_mean(static.elementwise_mul(
            static.softmax(w), h))
    opt, report = passes_mod.apply_passes(
        main, ["x", "w"], [out.name], _strategy(amp="bfloat16"))
    assert opt.global_block.vars["x"].dtype == "bfloat16"
    assert opt.global_block.vars["w"].dtype == "float32"
    assert report.amp["amp_lowprec_feeds"] == 1
    # the executor's host-cast map makes the same call
    amp = passes_mod.resolve_amp(_strategy(amp="bfloat16"))
    fdt = amp_feed_dtypes(main.global_block, amp)
    assert "x" in fdt and "w" not in fdt


def test_amp_py_reader_stages_low_from_first_batch():
    """Review regression: batches prefetched before the first run used
    to stage f32 (no stash yet) and force a second compile."""
    from paddle_tpu.framework.errors import EOFException

    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup = static.Program(), static.Program()
        main.random_seed = startup.random_seed = 9
        with static.program_guard(main, startup):
            reader = static.py_reader(
                capacity=4, shapes=[(-1, 8), (-1, 1)],
                dtypes=["float32", "int64"], name="amp_pr")
            x, y = static.read_file(reader)
            loss = static.mean(static.softmax_with_cross_entropy(
                static.nn.fc(x, 4), y))
            static.SGD(0.1).minimize(loss)

        def gen():
            rng = np.random.RandomState(0)
            for _ in range(4):
                yield (rng.randn(8, 8).astype(np.float32),
                       rng.randint(0, 4, (8, 1)).astype(np.int64))

        reader.decorate_batch_generator(gen)
        exe = static.Executor()
        exe.run(startup)
        cp = static.CompiledProgram(main,
                                    build_strategy=_strategy(
                                        amp="bfloat16"))
        # the construction-time stash means the reader stages bf16
        # before any run has happened
        assert main._amp_feed_dtypes and \
            str(main._amp_feed_dtypes["amp_pr.slot0"]) == "bfloat16"
        for _epoch in range(2):
            reader.start()
            while True:
                try:
                    exe.run(cp, fetch_list=[loss])
                except EOFException:
                    reader.reset()
                    break
        assert exe.counters["compile_cache_misses"] == 1, \
            "first prefetched batch staged f32 -> double compile"


def test_amp_device_staged_feed_recast_to_run_dtype():
    """Review regression: the program-level _amp_feed_dtypes stash is
    shared, so a prefetch thread can stage a batch for the OTHER amp
    config; the executor must re-cast device arrays to this run's
    dtype instead of feeding the wrong graph or recompiling forever."""
    import jax.numpy as jnp

    scope = static.Scope()
    with static.scope_guard(scope):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 8])
            out = static.reduce_mean(static.nn.fc(x, 4))
        exe = static.Executor()
        exe.run(startup)
        host = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        # stale bf16 staging into an amp-OFF run: cast up, f32 graph
        r_off = exe.run(static.CompiledProgram(main, _strategy()),
                        feed={"x": jnp.asarray(host, jnp.bfloat16)},
                        fetch_list=[out])[0]
        assert r_off.dtype == np.float32
        # stale f32 staging into an amp-ON run: cast down — same
        # executable as a host-cast bf16 feed (no second compile)
        cp_on = static.CompiledProgram(main,
                                       _strategy(amp="bfloat16"))
        exe.run(cp_on, feed={"x": host}, fetch_list=[out])
        misses = exe.counters["compile_cache_misses"]
        exe.run(cp_on, feed={"x": jnp.asarray(host)}, fetch_list=[out])
        assert exe.counters["compile_cache_misses"] == misses, \
            "device f32 feed recompiled the amp executable"


# ---------------------------------------------------------------------------
# fp16 loss scaling
# ---------------------------------------------------------------------------
def test_fp16_threads_check_finite_and_unscale():
    main, _, loss = _train_program()
    opt, report = passes_mod.apply_passes(
        main, ["x", "label"], [loss.name], _strategy(amp="float16"))
    types = [op.type for op in opt.global_block.ops]
    assert "check_finite_and_unscale" in types
    assert report.amp.get("amp_loss_scaled") == 1
    i_bwd = types.index("backward")
    # scale feeds the backward, unscale follows it
    assert types[i_bwd - 1] == "scale"
    assert types[i_bwd + 1] == "check_finite_and_unscale"
    (bwd,) = [op for op in opt.global_block.ops if op.type == "backward"]
    assert bwd.inputs["Loss"][0].endswith("@amp.scaled")
    # review regression: FoundInfinite must gate the update ops — a
    # non-finite step skips params AND moments, not just zeroes grads
    updates = [op for op in opt.global_block.ops if op.type == "sgd"]
    assert updates
    for op in updates:
        assert op.inputs.get("FoundInfinite") == ["found_inf@amp"], \
            op.to_dict()


def test_update_kernels_skip_on_found_inf():
    import jax.numpy as jnp

    from paddle_tpu.static.kernels import KERNELS, ExecContext

    p = jnp.asarray([1.0, 2.0], jnp.float32)
    g = jnp.asarray([0.5, 0.5], jnp.float32)
    m = jnp.asarray([0.1, 0.1], jnp.float32)
    v = jnp.asarray([0.2, 0.2], jnp.float32)
    one = jnp.asarray([1.0], jnp.float32)
    lr = jnp.asarray([0.1], jnp.float32)
    ins = {"Param": [p], "Grad": [g], "Moment1": [m], "Moment2": [v],
           "Beta1Pow": [one * 0.9], "Beta2Pow": [one * 0.999],
           "LearningRate": [lr]}
    for flag, changed in ((False, True), (True, False)):
        got = KERNELS["adam"](
            dict(ins, FoundInfinite=[jnp.asarray([flag])]),
            {}, ExecContext())
        moved = not np.array_equal(np.asarray(got["ParamOut"][0]),
                                   np.asarray(p))
        assert moved == changed, (flag, got)
        if not changed:   # skipped step: moments and beta-pows held too
            np.testing.assert_array_equal(
                np.asarray(got["Moment1Out"][0]), np.asarray(m))
            np.testing.assert_array_equal(
                np.asarray(got["Beta1PowOut"][0]), np.asarray(one * 0.9))
    # without the input the kernel behaves exactly as before
    got = KERNELS["sgd"]({"Param": [p], "Grad": [g],
                          "LearningRate": [lr]}, {}, ExecContext())
    np.testing.assert_allclose(np.asarray(got["ParamOut"][0]),
                               np.asarray(p - 0.1 * g))


def test_check_finite_and_unscale_kernel():
    import jax.numpy as jnp

    from paddle_tpu.static.kernels import KERNELS, ExecContext

    fn = KERNELS["check_finite_and_unscale"]
    g = jnp.asarray([2.0, 4.0], jnp.float32)
    out = fn({"X": [g]}, {"scale": 2.0}, ExecContext())
    np.testing.assert_array_equal(np.asarray(out["Out"][0]), [1.0, 2.0])
    assert not bool(out["FoundInfinite"][0][0])
    bad = jnp.asarray([1.0, np.inf], jnp.float32)
    out = fn({"X": [g, bad]}, {"scale": 2.0}, ExecContext())
    assert bool(out["FoundInfinite"][0][0])
    # non-finite step: every grad zeroed -> optimizer no-op
    for o in out["Out"]:
        np.testing.assert_array_equal(np.asarray(o), [0.0, 0.0])


# ---------------------------------------------------------------------------
# amp.decorate satellites (master_weight / save_dtype)
# ---------------------------------------------------------------------------
def test_decorate_master_weight_keeps_f32_masters():
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import amp, nn, optimizer

    paddle.seed(0)
    m = nn.Linear(4, 3)
    o = optimizer.Momentum(parameters=m.parameters(), learning_rate=0.1)
    m, o = amp.decorate(m, o, level="O2", dtype="bfloat16",
                        master_weight=True)
    p = m.parameters()[0]
    assert str(p.value.dtype) == "bfloat16"
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype(
        np.float32)).astype("bfloat16")
    before = np.asarray(p.value.astype(jnp.float32)).copy()
    loss = paddle.mean(m(x))
    loss.backward()
    o.step()
    slot = o._slots[id(p)]
    assert str(slot["__master__"].dtype) == "float32"
    assert str(slot["velocity"].dtype) == "float32"
    assert str(p.value.dtype) == "bfloat16"
    assert not np.array_equal(
        before, np.asarray(p.value.astype(jnp.float32)))
    # compute param is exactly the cast-down of the master
    np.testing.assert_array_equal(
        np.asarray(p.value),
        np.asarray(slot["__master__"].astype(jnp.bfloat16)))
    # masters ride the optimizer checkpoint
    assert any(k.endswith("@__master__") for k in o.state_dict())


def test_decorate_after_warmup_upgrades_existing_slots():
    """Review regression: step-then-decorate used to leave master-less
    slots, and the next step silently promoted the param back to f32."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import amp, nn, optimizer

    paddle.seed(0)
    m = nn.Linear(4, 3)
    o = optimizer.Adam(parameters=m.parameters(), learning_rate=1e-2)
    x32 = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype(
        np.float32))
    paddle.mean(m(x32)).backward()
    o.step()                      # slots exist, no masters yet
    m, o = amp.decorate(m, o, level="O2", dtype="bfloat16",
                        master_weight=True)
    p = m.parameters()[0]
    slot = o._slots[id(p)]
    assert "__master__" in slot
    assert str(slot["__master__"].dtype) == "float32"
    paddle.mean(m(x32.astype("bfloat16"))).backward()
    o.step()
    assert str(p.value.dtype) == "bfloat16", \
        "post-decorate step reverted the param to f32"
    np.testing.assert_array_equal(
        np.asarray(p.value),
        np.asarray(o._slots[id(p)]["__master__"].astype(jnp.bfloat16)))


def test_optimizer_multi_precision_kwarg_honored():
    """Review regression: subclasses swallowed multi_precision in **kw."""
    from paddle_tpu import optimizer

    for cls in (optimizer.Adam, optimizer.AdamW, optimizer.Momentum,
                optimizer.SGD, optimizer.Lamb, optimizer.RMSProp):
        o = cls(learning_rate=1e-3, parameters=[],
                multi_precision=True)
        assert o._multi_precision is True, cls.__name__


def test_ir_passes_escape_also_disables_amp_feed_cast(monkeypatch):
    """Review regression: PADDLE_IR_PASSES=0 disabled the graph rewrite
    but the executor still cast feeds bf16 — a bitwise-f32 escape that
    wasn't. Both must switch together."""
    base, _ = _f32_leg()
    monkeypatch.setenv("PADDLE_IR_PASSES", "0")
    monkeypatch.setenv("PADDLE_AMP", "bf16")
    losses, counters = _run_leg(_strategy(amp="bfloat16"))
    assert losses == base, "escape hatch changed numerics"
    assert counters.get("amp_casts_inserted", 0) == 0


def test_decorate_master_weight_false_opts_out():
    import paddle_tpu as paddle
    from paddle_tpu import amp, nn, optimizer

    paddle.seed(0)
    m = nn.Linear(4, 3)
    o = optimizer.SGD(parameters=m.parameters(), learning_rate=0.1)
    amp.decorate(m, o, level="O2", dtype="bfloat16", master_weight=False)
    assert o._multi_precision is False


def test_decorate_save_dtype_pins_state_dict():
    import paddle_tpu as paddle
    from paddle_tpu import amp, nn

    paddle.seed(0)
    m = nn.Linear(4, 3)
    amp.decorate(m, level="O2", dtype="bfloat16", save_dtype="float32")
    assert all(str(p.value.dtype) == "bfloat16" for p in m.parameters())
    sd = m.state_dict()
    assert all(str(v.dtype) == "float32" for v in sd.values())
    # live params untouched by the save cast
    assert all(str(p.value.dtype) == "bfloat16" for p in m.parameters())
    # review regression: loading must hit the LIVE params, not the
    # save-cast copies state_dict hands out
    ones = {k: np.ones_like(np.asarray(v.value, np.float32))
            for k, v in sd.items()}
    m.set_state_dict(ones)
    for p in m.parameters():
        assert str(p.value.dtype) == "bfloat16"
        np.testing.assert_array_equal(
            np.asarray(p.value, np.float32), 1.0)


# ---------------------------------------------------------------------------
# tools/dump_passes.py --amp
# ---------------------------------------------------------------------------
def test_dump_passes_amp_table():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dump_passes.py"),
         "--demo", "--amp"], env=env, capture_output=True, text=True,
        timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "auto_mixed_precision" in out.stdout
    assert "lowprec" in out.stdout
    assert "f32-pinned" in out.stdout
    assert "amp_casts_inserted" in out.stdout
