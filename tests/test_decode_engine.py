"""LLM decode engine (inference/decode): paged KV pool accounting,
output parity against the dense greedy oracle (mixed lengths,
continuous arrival, preemption under pool pressure, TP sharding,
escape legs), PR 6 admission semantics, drain, and the decode metric
family."""
import numpy as np
import pytest

from paddle_tpu.inference.decode import (DecodeEngine, DecodeModelConfig,
                                         DecodeScheduler, PageTableManager,
                                         init_decode_params,
                                         reference_generate)
from paddle_tpu.inference.serving import (DeadlineExceeded, EngineStopped,
                                          Overloaded)

CFG = DecodeModelConfig(vocab_size=32, n_layers=2, n_heads=2, head_dim=8,
                        ffn_dim=32, max_context=64)


def _drive(eng, max_ticks=500):
    for _ in range(max_ticks):
        if not eng.sched.pending():
            return
        eng.run_once()
    raise AssertionError("engine did not drain the workload")


@pytest.fixture(scope="module")
def engine():
    eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()
    return eng


@pytest.fixture(scope="module")
def ref_params():
    return init_decode_params(CFG, 3)


# ---------------------------------------------------------------------------
# paged KV pool manager
# ---------------------------------------------------------------------------
def test_pool_alloc_free_accounting():
    pool = PageTableManager(n_pages=8, page_size=4, max_pages_per_seq=4)
    assert pool.capacity == 7 and pool.pages_in_use == 0
    pages = pool.alloc_seq(1, 9)            # ceil(9/4) = 3 pages
    assert len(pages) == 3 and 0 not in pages
    assert pool.pages_in_use == 3
    # grow within the tail page: no new allocation
    assert pool.append_token(1, 10) is None
    assert pool.append_token(1, 13) not in (None, -1)  # 4th page
    assert pool.pages_in_use == 4
    # table row: pages then -1 padding
    row = pool.table_row(1)
    assert list(row[:4]) == pool.seq_pages(1) and row[-1] == -1 \
        if len(row) > 4 else True
    # per-seq budget exhausted
    assert pool.append_token(1, 17) == -1
    assert pool.free_seq(1) == 4 and pool.pages_in_use == 0
    assert pool.peak_pages_in_use == 4


def test_pool_eviction_counts():
    pool = PageTableManager(n_pages=6, page_size=4, max_pages_per_seq=4)
    pool.alloc_seq(1, 8)
    pool.alloc_seq(2, 8)
    assert pool.alloc_seq(3, 8) is None     # 5 allocatable, 4 used
    assert pool.evict_seq(2) == 2
    assert pool.evicted_pages == 2
    assert pool.alloc_seq(3, 8) is not None
    assert pool.pages_in_use == 4


def test_pool_reserves_trash_page():
    pool = PageTableManager(n_pages=4, page_size=2, max_pages_per_seq=3)
    pages = pool.alloc_seq(1, 6)
    assert pages is not None and 0 not in pages
    with pytest.raises(ValueError):
        PageTableManager(n_pages=1, page_size=2, max_pages_per_seq=1)


# ---------------------------------------------------------------------------
# output parity: the core correctness gate
# ---------------------------------------------------------------------------
def test_mixed_length_batch_matches_dense_oracle(engine, ref_params):
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10], [11, 12]]
    handles = [engine.submit(p, max_new_tokens=6) for p in prompts]
    _drive(engine)
    outs = [h.result(timeout=5) for h in handles]
    refs = [reference_generate(CFG, ref_params, p, 6) for p in prompts]
    assert outs == refs


def test_continuous_arrival_joins_running_batch(engine, ref_params):
    """A request submitted mid-generation joins the live decode batch
    (continuous batching) and both streams stay correct."""
    h1 = engine.submit([7, 3, 1, 2], max_new_tokens=10)
    for _ in range(4):
        engine.run_once()
    assert not h1.done()
    h2 = engine.submit([9, 8], max_new_tokens=5)
    _drive(engine)
    assert h1.result(timeout=5) == reference_generate(
        CFG, ref_params, [7, 3, 1, 2], 10)
    assert h2.result(timeout=5) == reference_generate(
        CFG, ref_params, [9, 8], 5)


def test_preemption_under_pool_pressure_preserves_outputs():
    """A pool too small for both sequences forces eviction; the
    preempted request re-prefills and still emits the oracle tokens."""
    cfg = DecodeModelConfig(vocab_size=32, n_layers=1, n_heads=2,
                            head_dim=8, ffn_dim=16, max_context=24)
    eng = DecodeEngine(cfg, seed=7, max_batch=2, n_pages=8, page_size=4,
                       max_pages_per_seq=6)
    eng.warm()
    prompts = [[1, 2, 3, 4, 5], [6, 7, 8, 9, 10, 11]]
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    _drive(eng)
    params = init_decode_params(cfg, 7)
    assert [h.result(timeout=5) for h in hs] == \
        [reference_generate(cfg, params, p, 10) for p in prompts]
    c = eng.counters
    assert c["decode_preempted"] >= 1
    assert c["kv_page_evictions"] >= 1
    assert eng.pool.pages_in_use == 0       # everything released
    preempted = [h for h in hs if h.stats().get("preempted")]
    assert preempted, "no handle recorded its preemption"


def test_eos_stops_generation(engine, ref_params):
    ref = reference_generate(CFG, ref_params, [1, 2, 3], 6)
    eos = ref[2]
    ref_eos = reference_generate(CFG, ref_params, [1, 2, 3], 6,
                                 eos_id=eos)
    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                       max_pages_per_seq=8, eos_id=eos)
    eng.warm()
    h = eng.submit([1, 2, 3], max_new_tokens=6)
    _drive(eng)
    out = h.result(timeout=5)
    assert out == ref_eos and out[-1] == eos
    assert len(out) < 6          # the stop token really cut it short


def test_escape_leg_pinned_xla_is_bitwise(engine, ref_params,
                                          monkeypatch):
    """PADDLE_PAGED_ATTENTION=0 (forced XLA gather) produces the same
    token stream — the escape leg stays bitwise on the ints that
    matter."""
    hb = engine.submit([3, 1, 4, 1, 5], max_new_tokens=8)
    _drive(engine)
    base = hb.result(timeout=5)
    monkeypatch.setenv("PADDLE_PAGED_ATTENTION", "0")
    eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()
    h = eng.submit([3, 1, 4, 1, 5], max_new_tokens=8)
    _drive(eng)
    assert h.result(timeout=5) == base == reference_generate(
        CFG, ref_params, [3, 1, 4, 1, 5], 8)


def test_tp_sharded_engine_matches_unsharded():
    """PR 10 composition: a TP=2 engine (megatron shardings over the
    conftest's virtual CPU mesh) serves the same tokens as the
    unsharded engine."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU topology")
    cfg = DecodeModelConfig(vocab_size=32, n_layers=2, n_heads=4,
                            head_dim=8, ffn_dim=32, max_context=64)
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8]]

    def run(mesh_shape):
        eng = DecodeEngine(cfg, seed=5, max_batch=2, n_pages=32,
                           page_size=8, max_pages_per_seq=8,
                           mesh_shape=mesh_shape)
        eng.warm()
        hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        _drive(eng)
        return [h.result(timeout=5) for h in hs]

    single = run(None)
    assert run({"tp": 2}) == single
    params = init_decode_params(cfg, 5)
    assert single == [reference_generate(cfg, params, p, 8)
                      for p in prompts]


# ---------------------------------------------------------------------------
# admission semantics (PR 6 machinery, typed)
# ---------------------------------------------------------------------------
def _sched(clock=None, **kw):
    pool = PageTableManager(n_pages=16, page_size=4, max_pages_per_seq=8)
    kw.setdefault("max_batch", 2)
    return DecodeScheduler(pool, clock=clock or (lambda: 0.0), **kw)


def test_admission_queue_bound_sheds_typed():
    s = _sched(max_queue=2)
    s.submit([1], 4)
    s.submit([1], 4)
    with pytest.raises(Overloaded):
        s.submit([1], 4)
    assert s.queue_depth == 2


def test_admission_rate_limit_sheds_typed():
    t = [0.0]
    s = _sched(clock=lambda: t[0], rate_limit=1.0, burst=1)
    s.submit([1], 4)
    with pytest.raises(Overloaded):
        s.submit([1], 4)
    t[0] += 2.0                  # bucket refills
    s.submit([1], 4)
    with pytest.raises(ValueError):
        _sched(rate_limit=0.0)
    with pytest.raises(ValueError):
        _sched(rate_limit=1.0, burst=0)


def test_admission_unmakeable_deadline_typed():
    s = _sched(min_service_s=0.5)
    with pytest.raises(DeadlineExceeded):
        s.submit([1], 4, deadline_s=0.1)


def test_admission_oversized_request_refused():
    s = _sched()
    with pytest.raises(ValueError):
        s.submit([1] * 30, 10)   # 40 > 8 pages x 4 tokens
    with pytest.raises(ValueError):
        s.submit([], 4)


def test_queued_deadline_expires_typed():
    t = [0.0]
    s = _sched(clock=lambda: t[0])
    h = s.submit([1], 4, deadline_s=1.0)
    t[0] = 2.0
    expired = s.expire_queued(t[0])
    assert len(expired) == 1 and isinstance(h.error(), DeadlineExceeded)
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=0)


def test_stopped_engine_refuses_typed():
    s = _sched()
    s.accepting = False
    with pytest.raises(EngineStopped):
        s.submit([1], 4)


# ---------------------------------------------------------------------------
# lifecycle: threaded scheduler + drain
# ---------------------------------------------------------------------------
def test_threaded_start_generate_drain(ref_params):
    eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()
    assert not eng.ready
    eng.start()
    assert eng.ready
    out = eng.generate([2, 4, 6], max_new_tokens=5, timeout=30)
    assert out == reference_generate(CFG, ref_params, [2, 4, 6], 5)
    h = eng.submit([5, 5], max_new_tokens=4)
    assert eng.drain(timeout=30)
    assert h.result(timeout=5) == reference_generate(
        CFG, ref_params, [5, 5], 4)
    with pytest.raises(EngineStopped):
        eng.submit([1], 2)
    assert not eng.ready


def test_decode_step_failure_fails_typed_and_recovers(ref_params):
    """A runtime decode-step failure must fail every live request
    TYPED (never a silent hang in the scheduler loop) and rebuild the
    donated pool so later requests keep serving correctly."""
    from paddle_tpu.inference.serving import RequestFailed

    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()
    h1 = eng.submit([1, 2, 3], max_new_tokens=6)
    eng.run_once()               # prefill lands h1 in a slot

    def boom(*a, **k):
        raise RuntimeError("device fell over")

    real_step = eng._decode_step
    eng._decode_step = boom
    assert eng.run_once() >= 1   # the failure resolved work, not a hang
    with pytest.raises(RequestFailed):
        h1.result(timeout=0)
    assert eng.counters["decode_failed"] >= 1
    assert eng.pool.pages_in_use == 0
    # pool was rebuilt: a fresh request serves the oracle tokens
    eng._decode_step = real_step
    h2 = eng.submit([4, 5, 6], max_new_tokens=5)
    _drive(eng)
    assert h2.result(timeout=5) == reference_generate(
        CFG, ref_params, [4, 5, 6], 5)


def test_prefill_failure_fails_typed_and_recovers(ref_params):
    from paddle_tpu.inference.serving import RequestFailed

    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()

    def boom(*a, **k):
        raise RuntimeError("prefill fell over")

    real = dict(eng._prefill_steps)
    eng._prefill_steps = {n: boom for n in real}
    h1 = eng.submit([1, 2, 3], max_new_tokens=4)
    eng.run_once()
    with pytest.raises(RequestFailed):
        h1.result(timeout=0)
    assert eng.pool.pages_in_use == 0    # failed seq's pages released
    eng._prefill_steps = real
    h2 = eng.submit([1, 2, 3], max_new_tokens=4)
    _drive(eng)
    assert h2.result(timeout=5) == reference_generate(
        CFG, ref_params, [1, 2, 3], 4)


def test_sigterm_drain_duck_types():
    """serving.install_sigterm_drain drives any engine with a
    drain(timeout) — the decode engine reuses it verbatim."""
    import signal

    from paddle_tpu.inference.serving import install_sigterm_drain

    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=16, page_size=8,
                       max_pages_per_seq=4)
    prev = signal.getsignal(signal.SIGTERM)
    try:
        install_sigterm_drain(eng, exit_code=None)
        assert signal.getsignal(signal.SIGTERM) is not prev
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# observability: counters, histograms, cost gauges, /metrics
# ---------------------------------------------------------------------------
def test_counters_and_latency_stats(engine, ref_params):
    h = engine.submit([8, 6, 4], max_new_tokens=5)
    _drive(engine)
    h.result(timeout=5)
    c = engine.counters
    for key in ("decode_requests", "decode_tokens", "decode_steps",
                "decode_prefills", "kv_pages_in_use",
                "kv_page_evictions", "decode_batch_fill_pct"):
        assert key in c, c
    # substrate build accounting rode the engine's counter sink
    assert c["trace_ms"] > 0 and c["compile_ms"] > 0
    # cost gauges stay truthful on decode: live pages, not the pool
    assert c["step_model_flops"] > 0
    assert c["step_hbm_bytes"] > 0
    ls = engine.engine_latency_stats()
    assert ls["n"] > 0
    assert ls["e2e_p99_ms"] >= ls["e2e_p50_ms"] > 0
    assert ls["step_p99_ms"] >= ls["step_p50_ms"] > 0
    st = h.stats()
    assert st["ttft_ms"] > 0 and len(st["token_times"]) == 5


def test_decode_metric_family_scrapes():
    from paddle_tpu import profiler

    assert set(profiler.DECODE_COUNTER_NAMES) >= {
        "decode_requests", "decode_tokens", "kv_pages_in_use",
        "kv_page_evictions", "decode_batch_fill_pct"}
    text = profiler.render_prometheus()
    for name in ("kv_pages_in_use", "kv_page_evictions",
                 "decode_batch_fill_pct", "decode_e2e_ms",
                 "decode_step_ms", "decode_prefill_ms"):
        assert name in text, f"/metrics missing {name}"


def test_paged_decode_cost_counts_live_pages_not_pool():
    from paddle_tpu.static.cost_model import paged_decode_cost

    c = paged_decode_cost(CFG, [9, 17], page_size=8, itemsize=4)
    E = CFG.hidden
    # live page tokens: ceil(9/8)*8 + ceil(17/8)*8 = 16 + 24
    assert c["live_page_tokens"] == 40
    kv_bytes = 2 * CFG.n_layers * 40 * E * 4
    assert c["hbm_bytes"] >= kv_bytes
    assert c["model_flops"] > 0 and c["arith_intensity"] > 0
    # longer context -> more flops AND more page bytes
    c2 = paged_decode_cost(CFG, [57, 57], page_size=8, itemsize=4)
    assert c2["model_flops"] > c["model_flops"]
    assert c2["live_page_tokens"] == 128


def test_program_cost_paged_attention_op_rule():
    """The IR rule: a paged_attention op's hbm_bytes charge the
    GATHERED live pages (table entries x page bytes), never the whole
    pool operand."""
    from paddle_tpu.static.cost_model import program_cost
    from paddle_tpu.static.ir import Program

    prog = Program()
    b = prog.global_block
    b.create_var("q", shape=[4, 8, 64], dtype="float32")
    b.create_var("kp", shape=[1000, 128, 8, 64], dtype="float32")
    b.create_var("vp", shape=[1000, 128, 8, 64], dtype="float32")
    b.create_var("pt", shape=[4, 4], dtype="int32")
    b.create_var("lens", shape=[4], dtype="int32")
    b.create_var("out", shape=[4, 8, 64], dtype="float32")
    b.append_op("paged_attention",
                inputs={"Q": ["q"], "KPages": ["kp"], "VPages": ["vp"],
                        "PageTable": ["pt"], "SeqLens": ["lens"]},
                outputs={"Out": ["out"]})
    report = program_cost(prog)
    (op,) = report.ops
    live_tokens = 4 * 4 * 128
    live_kv_bytes = 2 * live_tokens * 8 * 64 * 4
    pool_bytes = 2 * 1000 * 128 * 8 * 64 * 4
    assert op.hbm_bytes >= live_kv_bytes
    assert op.hbm_bytes < pool_bytes // 10, \
        "pool bytes leaked into the paged-attention charge"
    assert op.flops == 4 * 8 * 64 * live_tokens


# ---------------------------------------------------------------------------
# decode load generator (tools/load_gen.py satellite)
# ---------------------------------------------------------------------------
def test_decode_load_gen_deterministic_summary():
    from tools.load_gen import DecodeLoadGen

    def run():
        eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32,
                           page_size=8, max_pages_per_seq=8)
        eng.warm()
        eng.start()
        try:
            gen = DecodeLoadGen(eng, total_requests=6, workers=2,
                                prompt_lens=(3, 7, 12),
                                output_lens=(4, 6), keep_outputs=True)
            return gen.run(), dict(gen.outputs)
        finally:
            eng.drain(timeout=30)

    s1, o1 = run()
    s2, o2 = run()
    assert o1 == o2, "decode workload content is not deterministic"
    assert s1["ok"] == 6 and s1["shed"] == 0 and s1["failed"] == 0
    assert s1["decode_tokens"] == s2["decode_tokens"] == 6 * 5  # (4+6)/2
    assert s1["decode_tokens_per_sec"] > 0
    for key in ("ttft_p50_ms", "ttft_p99_ms", "itl_p50_ms", "itl_p99_ms",
                "engine_p50_ms", "engine_p99_ms", "step_p50_ms"):
        assert key in s1, s1
    assert s1["ttft_p99_ms"] >= s1["ttft_p50_ms"] > 0
    assert s1["itl_p50_ms"] >= 0


# ---------------------------------------------------------------------------
# decode token economics: spec decode, int8 KV pages, prefix cache,
# sampling (this PR's plane)
# ---------------------------------------------------------------------------
from paddle_tpu.inference.decode import NgramProposer  # noqa: E402

LOOP_PROMPT = [5, 9, 2, 5, 9, 2, 5, 9, 2, 5, 9, 2]     # period-3 motif


def test_ngram_proposer_prompt_lookup():
    p = NgramProposer(max_n=3)
    # periodic context: the tail 3-gram recurs, continuation is the
    # cycle itself
    assert p.propose([1, 2, 3, 1, 2, 3, 1, 2], 3) == [3, 1, 2]
    # most RECENT prior occurrence wins
    assert p.propose([7, 1, 7, 5, 7], 2) == [5, 7]
    # falls back to shorter n-grams before giving up
    assert p.propose([4, 8, 9, 8], 2) == [9, 8]
    assert p.propose([1, 2, 3], 2) == []           # no recurrence
    assert p.propose([1], 3) == []                 # too short
    assert p.propose([1, 1], 0) == []              # k=0
    with pytest.raises(ValueError):
        NgramProposer(max_n=0)


def _spec_engine(spec_k=3, **kw):
    eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32, page_size=8,
                       max_pages_per_seq=8, spec_k=spec_k,
                       proposer=NgramProposer(), **kw)
    eng.warm()
    return eng


def test_spec_decode_matches_dense_oracle_mixed_lengths(ref_params):
    """The tentpole gate: speculative decoding is EXACT under greedy —
    bitwise the oracle's tokens over mixed lengths — while the
    telemetry shows real drafting happened."""
    eng = _spec_engine()
    prompts = [LOOP_PROMPT, [4, 5, 6, 7, 8, 9, 10], [11, 12]]
    handles = [eng.submit(p, max_new_tokens=10) for p in prompts]
    _drive(eng)
    outs = [h.result(timeout=5) for h in handles]
    assert outs == [reference_generate(CFG, ref_params, p, 10)
                    for p in prompts]
    c = eng.counters
    assert c["spec_proposed"] > 0
    assert 0 <= c["spec_accepted"] <= c["spec_proposed"]
    assert c["spec_accept_rate"] == pytest.approx(
        c["spec_accepted"] / max(1, c["spec_proposed"]), abs=1e-3)
    # accepted drafts are steps never run: the loop-prone prompt must
    # have bought at least one multi-token step
    assert c["spec_accepted"] > 0
    assert c["decode_steps"] < sum(10 for _ in prompts)


def test_spec_continuous_arrival_joins_running_batch(ref_params):
    eng = _spec_engine()
    h1 = eng.submit(LOOP_PROMPT, max_new_tokens=10)
    for _ in range(3):
        eng.run_once()
    assert not h1.done()
    h2 = eng.submit([9, 8], max_new_tokens=5)
    _drive(eng)
    assert h1.result(timeout=5) == reference_generate(
        CFG, ref_params, LOOP_PROMPT, 10)
    assert h2.result(timeout=5) == reference_generate(
        CFG, ref_params, [9, 8], 5)


def test_spec_preemption_under_pool_pressure_preserves_outputs():
    """Draft growth never preempts a peer: under pool pressure the
    engine shrinks k instead, and a preempted request still re-prefills
    to the oracle's tokens."""
    cfg = DecodeModelConfig(vocab_size=32, n_layers=1, n_heads=2,
                            head_dim=8, ffn_dim=16, max_context=24)
    eng = DecodeEngine(cfg, seed=7, max_batch=2, n_pages=8, page_size=4,
                       max_pages_per_seq=6, spec_k=2,
                       proposer=NgramProposer())
    eng.warm()
    prompts = [[1, 2, 3, 4, 5], [6, 7, 8, 9, 10, 11]]
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    _drive(eng)
    params = init_decode_params(cfg, 7)
    assert [h.result(timeout=5) for h in hs] == \
        [reference_generate(cfg, params, p, 10) for p in prompts]
    assert eng.pool.pages_in_use == 0


def test_spec_escape_env_pins_dense_step(ref_params, monkeypatch):
    """PADDLE_SPEC_DECODE=0 forces the plain one-token step even when
    spec_k is configured — bitwise the oracle, zero drafts."""
    monkeypatch.setenv("PADDLE_SPEC_DECODE", "0")
    eng = _spec_engine()
    h = eng.submit(LOOP_PROMPT, max_new_tokens=8)
    _drive(eng)
    assert h.result(timeout=5) == reference_generate(
        CFG, ref_params, LOOP_PROMPT, 8)
    c = eng.counters
    assert c.get("spec_proposed", 0) == 0
    # prefill emits the first token; each remaining token is exactly
    # one plain decode step — no multi-token acceptances anywhere
    assert c["decode_steps"] == 7


def test_spec_requires_greedy_temperature():
    with pytest.raises(ValueError, match="temperature"):
        DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                     max_pages_per_seq=8, spec_k=2,
                     proposer=NgramProposer(), temperature=0.7)
    with pytest.raises(ValueError):
        DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                     max_pages_per_seq=8, kv_codec="int4")


def test_int8_kv_engine_matches_oracle(ref_params):
    """kv_codec=int8: pools allocate as int8 (+ per-row scale planes)
    and greedy outputs still match the f32 dense oracle — the quant
    error stays under the logit margins at these scales."""
    import jax.numpy as jnp

    eng = DecodeEngine(CFG, seed=3, max_batch=3, n_pages=32, page_size=8,
                       max_pages_per_seq=8, kv_codec="int8")
    eng.warm()
    assert eng._k_pages.dtype == jnp.int8
    assert eng._k_scales is not None and \
        eng._k_scales.shape == eng._k_pages.shape[:3]
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10], [11, 12]]
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    _drive(eng)
    assert [h.result(timeout=5) for h in handles] == \
        [reference_generate(CFG, ref_params, p, 6) for p in prompts]
    snap = eng.kv_debug_snapshot()
    assert snap["kv_codec"] == "int8"


def test_spec_over_int8_pool_matches_oracle(ref_params):
    """The two legs compose: speculative verify over quantized pages
    still emits the oracle's tokens."""
    eng = _spec_engine(kv_codec="int8")
    h = eng.submit(LOOP_PROMPT, max_new_tokens=10)
    _drive(eng)
    assert h.result(timeout=5) == reference_generate(
        CFG, ref_params, LOOP_PROMPT, 10)
    assert eng.counters["spec_proposed"] > 0


def test_prefix_cache_repeat_prompt_hits_and_matches(ref_params):
    """The same prompt twice: the second prefill consumes the shared-
    prefix index (kv_prefix_hits = full prompt pages) and the outputs
    stay identical — shared pages are read-only for the consumer."""
    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32, page_size=8,
                       max_pages_per_seq=8)
    eng.warm()
    prompt = list(range(1, 18))                    # 17 toks: 2 full pages
    h1 = eng.submit(prompt, max_new_tokens=6)
    _drive(eng)
    out1 = h1.result(timeout=5)
    assert eng.counters["kv_prefix_hits"] == 0
    reclaimed_before = eng.pool.snapshot()["cached_reclaimed"]
    h2 = eng.submit(prompt, max_new_tokens=6)
    _drive(eng)
    out2 = h2.result(timeout=5)
    assert out1 == out2 == reference_generate(CFG, ref_params, prompt, 6)
    assert eng.counters["kv_prefix_hits"] == 2     # (17-1)//8 pages
    # the hit revived cached pages — it did not allocate-and-recompute
    assert eng.pool.snapshot()["cached_reclaimed"] == reclaimed_before


def test_engine_cow_hook_copies_device_page():
    """_maybe_cow is the defensive engine hook: when a slot's write
    position lands on a shared page, the page is copied on device and
    the slot's table repoints — other holders keep reading the
    original bytes."""
    from types import SimpleNamespace

    import jax.numpy as jnp

    eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=16, page_size=8,
                       max_pages_per_seq=4)
    eng.warm()
    pool = eng.pool
    toks = list(range(8))
    p1 = pool.alloc_seq(101, 8)
    pool.register_prefix(101, toks)
    shared = pool.match_prefix(toks + [9])
    pool.alloc_seq_shared(102, shared, 9)
    eng._k_pages = eng._k_pages.at[:, p1[0]].set(7.0)
    eng._maybe_cow(SimpleNamespace(seq_id=102, length=2))
    assert eng.counters.get("kv_cow_copies", 0) == 1
    dst = pool.seq_pages(102)[0]
    assert dst != p1[0] and pool.seq_pages(101)[0] == p1[0]
    np.testing.assert_allclose(np.asarray(eng._k_pages[:, dst]), 7.0)


def test_sampling_engine_deterministic_per_seed():
    """temperature > 0: same sample_seed -> the same token stream
    (host-seeded Gumbel noise through the fused kernel); tokens stay
    in-vocab."""
    def run(seed):
        eng = DecodeEngine(CFG, seed=3, max_batch=2, n_pages=32,
                           page_size=8, max_pages_per_seq=8,
                           temperature=0.8, top_k=5, sample_seed=seed)
        eng.warm()
        hs = [eng.submit([1, 2, 3], max_new_tokens=8),
              eng.submit([4, 5, 6, 7], max_new_tokens=8)]
        _drive(eng)
        return [h.result(timeout=5) for h in hs]

    a = run(42)
    assert a == run(42)
    assert all(0 <= t < CFG.vocab_size for out in a for t in out)
