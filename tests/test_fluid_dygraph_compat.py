"""Behavior checks for the fluid / fluid.dygraph / top-level surface
fill (the names the extended namespace freeze exposed): these must
compute, not just resolve."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.dygraph as dg
import paddle_tpu.static as static


def test_mode_flags_roundtrip():
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
        assert not static.in_dygraph_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()
    with dg.guard():
        assert dg.enabled()


def test_to_variable_and_manual_seed():
    v = paddle.to_variable(np.ones((2, 2), np.float32), name="v0")
    assert v.name == "v0" and tuple(v.shape) == (2, 2)
    paddle.manual_seed(7)
    a = paddle.randn([3])
    paddle.manual_seed(7)
    b = paddle.randn([3])
    np.testing.assert_array_equal(a.numpy(), b.numpy())


def test_gru_unit_matches_reference_formula():
    paddle.seed(0)
    unit = dg.GRUUnit(size=12)  # hidden 4
    h = 4
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 3 * h).astype(np.float32))
    hp = paddle.to_tensor(rng.randn(2, h).astype(np.float32))
    new_h, reset_h, gate = unit(x, hp)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    w = unit.weight.numpy()
    b = unit.bias.numpy()
    xv, hv = x.numpy(), hp.numpy()
    u = sig(xv[:, :h] + hv @ w[:, :h] + b[0, :h])
    r = sig(xv[:, h:2 * h] + hv @ w[:, h:2 * h] + b[0, h:2 * h])
    c = np.tanh(xv[:, 2 * h:] + (r * hv) @ w[:, 2 * h:] + b[0, 2 * h:])
    exp = (1 - u) * hv + u * c
    np.testing.assert_allclose(new_h.numpy(), exp, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(reset_h.numpy(), r * hv, rtol=1e-5)


def test_nce_layer_trains():
    paddle.seed(0)
    layer = dg.NCE(num_total_classes=50, dim=8, num_neg_samples=5, seed=3)
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 50, (4, 1)).astype(np.int64))
    loss = layer(x, y).sum()
    loss.backward()
    assert layer.weight.grad is not None
    assert np.isfinite(float(loss.numpy()))


def test_prelu_modes():
    paddle.seed(0)
    p = dg.PRelu(mode="all")
    x = paddle.to_tensor(np.array([[-2.0, 3.0]], np.float32))
    out = p(x)
    np.testing.assert_allclose(out.numpy(), [[-0.5, 3.0]], rtol=1e-6)
    pc = dg.PRelu(mode="channel", channel=2)
    xc = paddle.to_tensor(np.ones((1, 2, 2, 2), np.float32) * -1)
    np.testing.assert_allclose(pc(xc).numpy(), -0.25)
    with pytest.raises(ValueError):
        dg.PRelu(mode="element")


def test_tree_conv_shapes_and_grad():
    paddle.seed(0)
    tc = dg.TreeConv(feature_size=6, output_size=5, num_filters=2)
    rng = np.random.RandomState(0)
    nodes = paddle.to_tensor(rng.randn(2, 4, 6).astype(np.float32))
    # node 0 has children 1,2; node 1 has child 3
    edges = paddle.to_tensor(np.asarray(
        [[[0, 1], [0, 2], [1, 3], [-1, -1]]] * 2, np.int64))
    out = tc(nodes, edges)
    assert tuple(out.shape) == (2, 4, 5, 2)
    out.sum().backward()
    assert tc.weight.grad is not None
    assert np.isfinite(tc.weight.grad.numpy()).all()


def test_save_load_dygraph_roundtrip(tmp_path):
    from paddle_tpu import nn

    paddle.seed(0)
    lin = nn.Linear(3, 2)
    path = str(tmp_path / "ckpt")
    dg.save_dygraph(lin.state_dict(), path)
    assert os.path.exists(path + ".pdparams")
    params, opt = dg.load_dygraph(path)
    assert opt is None
    np.testing.assert_array_equal(np.asarray(params["weight"]),
                                  lin.weight.numpy())


def test_traced_layer_runs_and_saves(tmp_path):
    from paddle_tpu import nn

    paddle.seed(0)
    lin = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    out, traced = dg.TracedLayer.trace(lin, [x])
    out2 = traced(x)
    np.testing.assert_allclose(np.asarray(out2.numpy()),
                               np.asarray(out.numpy()), rtol=1e-6)
    # the StableHLO export round-trips through jit.load
    path = str(tmp_path / "traced")
    traced.save_inference_model(path)
    loaded = paddle.jit.load(path)
    y = loaded(x.numpy())
    np.testing.assert_allclose(np.asarray(y), out.numpy(), rtol=1e-5)


def test_device_guard_records_op_device():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2])
        with static.device_guard("gpu:1"):
            y = static.nn.fc(x, size=2)
    devices = [op.attrs.get("op_device") for op in main.global_block.ops]
    assert "gpu:1" in devices


def test_datafeed_desc_parses_proto_text(tmp_path):
    proto = tmp_path / "feed.prototxt"
    proto.write_text("""
name: "MultiSlotDataFeed"
batch_size: 2
multi_slot_desc {
  slots {
    name: "words"
    type: "uint64"
    is_dense: false
    is_used: true
  }
  slots {
    name: "label"
    type: "float"
    is_dense: true
    shape: 1
    is_used: true
  }
}
""")
    desc = static.DataFeedDesc(str(proto))
    slots = desc.slots()
    assert [s.name for s in slots] == ["words", "label"]
    assert slots[0].type == "uint64" and slots[0].dense_dim is None
    assert slots[1].type == "float" and slots[1].dense_dim == 1


def test_trainer_descs_and_dispatchers():
    td = static.DistMultiTrainer()
    td.set_thread(4)
    assert td.thread_num == 4 and td._kind == "DistMultiTrainer"
    rr = static.RoundRobin(["a:1", "b:2"])
    assert rr.dispatch(["v1", "v2", "v3"]) == ["a:1", "b:2", "a:1"]
    hn = static.HashName(["a:1", "b:2"])
    one = hn.dispatch(["w"] )
    assert hn.dispatch(["w"]) == one  # stable


def test_memory_passes_warn_noop():
    with pytest.warns(DeprecationWarning):
        static.memory_optimize(None)
    with pytest.warns(DeprecationWarning):
        static.release_memory(None)


def test_generator_and_require_version():
    g = static.Generator().manual_seed(5)
    assert g.initial_seed() == 5
    static.require_version("0.0.1")
    with pytest.raises(RuntimeError):
        static.require_version("999.0.0")


def test_lod_tensor_array():
    arr = static.LoDTensorArray()
    arr.append(np.ones((2, 2)))
    assert len(arr) == 1
    with pytest.raises(TypeError):
        arr.append("nope")


def test_save_dygraph_optimizer_state_suffix(tmp_path):
    import jax.numpy as jnp

    path = str(tmp_path / "opt")
    # all-tensor accumulator dict: the @slot key convention must still
    # route to .pdopt (review finding)
    dg.save_dygraph({"linear_0.w_0@velocity_0": jnp.ones(3)}, path)
    assert os.path.exists(path + ".pdopt")
    params, opt = dg.load_dygraph(path)
    assert params is None and opt is not None


def test_load_dygraph_suffixed_path_and_missing(tmp_path):
    from paddle_tpu import nn

    paddle.seed(0)
    lin = nn.Linear(2, 2)
    path = str(tmp_path / "m")
    dg.save_dygraph(lin.state_dict(), path)
    params, _ = dg.load_dygraph(path + ".pdparams")  # suffixed accepted
    assert params is not None
    with pytest.raises(ValueError, match="neither"):
        dg.load_dygraph(str(tmp_path / "nope"))


def test_datafeed_desc_use_slots_filters(tmp_path):
    proto = tmp_path / "f.prototxt"
    proto.write_text('slots {\n name: "a"\n type: "uint64"\n}\n'
                     'slots {\n name: "b"\n type: "float"\n is_dense: '
                     'true\n shape: 1\n}\n')
    desc = static.DataFeedDesc(str(proto))
    desc.set_use_slots(["b"])
    assert [s.name for s in desc.slots()] == ["b"]
