"""Tests for the long-tail tensor ops added for API completeness
(reference operators: searchsorted_op, unique_consecutive_op, trapezoid,
and the math jnp wrappers), plus the PRNG impl flag."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a):
    return paddle.to_tensor(np.asarray(a))


def test_nanmedian():
    x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], np.float32)
    np.testing.assert_allclose(paddle.nanmedian(t(x)).numpy(),
                               np.nanmedian(x))


def test_rad2deg_deg2rad_roundtrip():
    x = np.linspace(-3, 3, 7).astype(np.float32)
    got = paddle.deg2rad(paddle.rad2deg(t(x)))
    np.testing.assert_allclose(got.numpy(), x, rtol=1e-6)


def test_ldexp():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    e = np.array([1, 2, 3], np.int32)
    np.testing.assert_allclose(paddle.ldexp(t(x), t(e)).numpy(),
                               np.ldexp(x, e))


def test_polygamma():
    # polygamma(1, 1) = trigamma(1) = pi^2/6
    got = paddle.polygamma(t(np.array([1.0], np.float32)), 1)
    np.testing.assert_allclose(got.numpy(), np.pi ** 2 / 6, rtol=1e-5)


def test_trapezoid():
    y = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(paddle.trapezoid(t(y)).numpy(), 4.0)
    x = np.array([0.0, 1.0, 3.0], np.float32)
    np.testing.assert_allclose(paddle.trapezoid(t(y), x=t(x)).numpy(),
                               np.trapezoid(y, x))
    np.testing.assert_allclose(paddle.trapezoid(t(y), dx=0.5).numpy(),
                               np.trapezoid(y, dx=0.5))


def test_bucketize():
    edges = np.array([1.0, 3.0, 5.0], np.float32)
    x = np.array([0.5, 1.0, 2.0, 5.0, 9.0], np.float32)
    got = paddle.bucketize(t(x), t(edges))
    np.testing.assert_array_equal(got.numpy(),
                                  np.searchsorted(edges, x, side="left"))
    got_r = paddle.bucketize(t(x), t(edges), right=True, out_int32=True)
    np.testing.assert_array_equal(got_r.numpy(),
                                  np.searchsorted(edges, x, side="right"))
    assert got_r.numpy().dtype == np.int32


def test_unique_consecutive():
    x = np.array([1, 1, 2, 2, 2, 3, 1, 1], np.int64)
    out, inv, cnt = paddle.unique_consecutive(
        t(x), return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
    np.testing.assert_array_equal(cnt.numpy(), [2, 3, 1, 2])
    np.testing.assert_array_equal(out.numpy()[inv.numpy()], x)


def test_unique_consecutive_axis():
    x = np.array([[1, 2], [1, 2], [3, 4]], np.int64)
    out = paddle.unique_consecutive(t(x), axis=0)
    np.testing.assert_array_equal(out.numpy(), [[1, 2], [3, 4]])


def test_as_strided():
    x = np.arange(12, dtype=np.float32)
    # sliding windows of 3, step 2 -> shape (5, 3), strides (2, 1)
    got = paddle.as_strided(t(x), [5, 3], [2, 1]).numpy()
    expect = np.lib.stride_tricks.as_strided(
        x, shape=(5, 3), strides=(8, 4))
    np.testing.assert_array_equal(got, expect)
    # offset
    got2 = paddle.as_strided(t(x), [2, 2], [4, 1], offset=1).numpy()
    np.testing.assert_array_equal(got2, [[1, 2], [5, 6]])


def test_view_reshape_and_bitcast():
    x = np.arange(8, dtype=np.float32)
    assert tuple(paddle.view(t(x), [2, 4]).shape) == (2, 4)
    bits = paddle.view(t(x), "int32")
    assert bits.numpy().dtype == np.int32
    np.testing.assert_array_equal(bits.numpy(),
                                  x.view(np.int32))


def test_prng_impl_flag_resolution():
    from paddle_tpu.framework import random as random_mod
    from paddle_tpu.framework.flags import get_flag

    assert get_flag("prng_impl") == "auto"
    impl = random_mod.prng_impl()
    # conftest forces the cpu backend -> threefry
    assert impl == "threefry2x32"
    key = random_mod.make_key(0)
    import jax
    assert str(jax.random.key_impl(key)) == impl
