"""OpTest harness: declarative op unit tests with numeric grad checking.

Replicates the reference's single most important test fixture
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:170):
subclasses declare op_type / inputs / attrs / expected outputs;
check_output builds a tiny Program, runs it through the real Executor
lowering (jit-compiled, CPU backend in tests) and compares against the
numpy reference; check_grad compares the framework's analytic gradients
(traced-vjp backward, static/backward.py) against central finite
differences (reference get_numeric_gradient op_test.py:57, delta=0.005).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu.static as static


class OpTestCase:
    op_type: str = None
    # slot -> np.ndarray | list[np.ndarray]; integer dtypes are fed as-is
    inputs: Dict[str, object] = {}
    attrs: Dict[str, object] = {}
    # slot -> expected np.ndarray | list[np.ndarray]
    outputs: Dict[str, object] = {}

    # -- plumbing ---------------------------------------------------------
    def _norm(self, slots):
        out = {}
        for k, v in slots.items():
            out[k] = list(v) if isinstance(v, (list, tuple)) else [v]
        return out

    def _build(self, extra_fetch: Sequence[str] = ()):
        ins = self._norm(self.inputs)
        outs_expected = self._norm(self.outputs)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            in_slots, feed = {}, {}
            for slot, arrays in ins.items():
                names = []
                for i, a in enumerate(arrays):
                    a = np.asarray(a)
                    name = f"{slot.lower()}_{i}"
                    static.data(name, list(a.shape), dtype=str(a.dtype))
                    names.append(name)
                    feed[name] = a
                in_slots[slot] = names
            out_slots = {}
            for slot, arrays in outs_expected.items():
                out_slots[slot] = [f"out_{slot.lower()}_{i}"
                                   for i in range(len(arrays))]
            blk = main.global_block
            op = blk.append_op(type=self.op_type, inputs=in_slots,
                               outputs=out_slots, attrs=dict(self.attrs))
            from paddle_tpu.static.layers import _infer_outputs
            _infer_outputs(blk, op, {})
        return main, startup, feed, out_slots, outs_expected

    # -- checks -----------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5):
        main, startup, feed, out_slots, expected = self._build()
        exe = static.Executor()
        fetch = [n for names in out_slots.values() for n in names]
        got = exe.run(main, feed=feed, fetch_list=fetch)
        got_by_name = dict(zip(fetch, got))
        for slot, arrays in expected.items():
            for name, want in zip(out_slots[slot], arrays):
                have = got_by_name[name]
                np.testing.assert_allclose(
                    np.asarray(have), np.asarray(want), atol=atol, rtol=rtol,
                    err_msg=f"{self.op_type}.{slot} ({name}) mismatch")

    def check_grad(self, inputs_to_check: Sequence[str], output_slot="Out",
                   output_index=0, max_relative_error=0.05, delta=5e-3,
                   atol=1e-3):
        """Compare analytic d(sum(out))/d(x) against central differences.

        inputs_to_check: feed var names, `slot` or `slot_i` style (the
        i-th array of a slot; bare slot means index 0).
        """
        main, startup, feed, out_slots, expected = self._build()
        out_name = out_slots[output_slot][output_index]
        check_names = []
        for s in inputs_to_check:
            s = s.lower()
            check_names.append(s if s in feed else f"{s}_0")

        with static.program_guard(main, startup):
            blk = main.global_block
            out_var = blk.var(out_name)
            loss = static.reduce_sum(out_var)
            grads = static.calc_gradient(loss, [blk.var(n)
                                                for n in check_names])
        exe = static.Executor()
        analytic = exe.run(main, feed=feed,
                           fetch_list=[g.name for g in grads])

        # numeric: rerun the forward program with perturbed feeds
        fwd, startup2, feed2, out_slots2, _ = self._build()
        with static.program_guard(fwd, startup2):
            loss2 = static.reduce_sum(fwd.global_block.var(
                out_slots2[output_slot][output_index]))
        exe2 = static.Executor()

        def loss_at(feed_override):
            out, = exe2.run(fwd, feed=feed_override,
                            fetch_list=[loss2])
            return float(out)

        for name, a_grad in zip(check_names, analytic):
            base = feed[name].astype(np.float32)
            num = np.zeros_like(base, dtype=np.float64).ravel()
            flat = base.ravel()
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + delta
                up = loss_at({**feed, name: base})
                flat[i] = orig - delta
                down = loss_at({**feed, name: base})
                flat[i] = orig
                num[i] = (up - down) / (2 * delta)
            num = num.reshape(base.shape)
            a = np.asarray(a_grad, dtype=np.float64)
            denom = np.maximum(np.abs(num), np.maximum(np.abs(a), 1e-3))
            rel = np.abs(a - num) / denom
            assert rel.max() <= max_relative_error, (
                f"{self.op_type} grad wrt {name}: max rel err "
                f"{rel.max():.4f} > {max_relative_error}\n"
                f"analytic={a.ravel()[:5]} numeric={num.ravel()[:5]}")


# -- shared finite-difference harness (used by test_grad_checks_r3/4/5) ----

def numeric_grad(f, x, delta=1e-3):
    """Central-difference gradient of scalar f at x (full tensor)."""
    import jax.numpy as jnp
    import numpy as np

    x = np.asarray(x, np.float32)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        fp = float(f(jnp.asarray(x)))
        flat[i] = orig - delta
        fm = float(f(jnp.asarray(x)))
        flat[i] = orig
        gf[i] = (fp - fm) / (2 * delta)
    return g


def check_grad(f, x, rtol=0.05, atol=5e-3, delta=1e-3):
    """jax.grad vs full-tensor central differences."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    analytic = np.asarray(jax.grad(f)(jnp.asarray(
        np.asarray(x, np.float32))))
    numeric = numeric_grad(f, x, delta)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)


def probe_check_grad(loss, x0, probes, rtol=0.08, atol=5e-3, delta=1e-2):
    """Central differences at selected probe indices — for kernels whose
    interpret-mode forwards make a full-tensor sweep impractical."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    analytic = np.asarray(jax.grad(loss)(jnp.asarray(x0)))
    for idx in probes:
        xp = x0.copy()
        xp[idx] += delta
        fp = float(loss(jnp.asarray(xp)))
        xp[idx] -= 2 * delta
        fm = float(loss(jnp.asarray(xp)))
        num = (fp - fm) / (2 * delta)
        np.testing.assert_allclose(analytic[idx], num, rtol=rtol,
                                   atol=atol, err_msg=str(idx))
