"""Parameter-server subsystem: native KV table, TCP service, communicator
modes, sparse embedding training (reference test pattern: multi-"node" on
localhost, SURVEY §4.3)."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ps import (
    AsyncCommunicator, GeoCommunicator, PSClient, PSServer, SparseEmbedding,
    SparseTable,
)

pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# table (native + python fallback parity)
# ---------------------------------------------------------------------------


@pytest.fixture(params=[False, True], ids=["native", "python"])
def force_python(request):
    return request.param


def test_table_pull_deterministic_init(force_python):
    t1 = SparseTable(4, seed=7, force_python=force_python)
    t2 = SparseTable(4, seed=7, force_python=force_python)
    ids = np.array([3, 99, 3, 12345678901], np.int64)
    np.testing.assert_allclose(t1.pull(ids), t2.pull(ids))
    assert t1.rows() == 3
    v = t1.pull(ids)
    np.testing.assert_allclose(v[0], v[2])
    assert np.all(np.abs(v) <= 0.01 + 1e-7)


def test_table_native_python_same_init():
    ids = np.array([5, 17, 23], np.int64)
    a = SparseTable(8, seed=3, force_python=False).pull(ids)
    b = SparseTable(8, seed=3, force_python=True).pull(ids)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_table_push_sgd_and_duplicates(force_python):
    t = SparseTable(2, optimizer="sgd", init_range=0.0,
                    force_python=force_python)
    ids = np.array([1, 1, 2], np.int64)
    grads = np.array([[1, 0], [1, 0], [0, 2]], np.float32)
    t.push(ids, grads, lr=0.5)
    out = t.pull(np.array([1, 2], np.int64))
    # duplicate id 1 accumulates sequentially: two SGD steps of -0.5*1
    np.testing.assert_allclose(out, [[-1.0, 0.0], [0.0, -1.0]])


def test_table_adagrad(force_python):
    t = SparseTable(1, optimizer="adagrad", init_range=0.0,
                    force_python=force_python)
    ids = np.array([7], np.int64)
    t.push(ids, np.array([[2.0]], np.float32), lr=1.0)
    # w -= lr * g / sqrt(g^2 + eps) = -2/sqrt(4) = -1
    np.testing.assert_allclose(t.pull(ids), [[-1.0]], rtol=1e-4)


def test_table_save_load_roundtrip(tmp_path, force_python):
    t = SparseTable(3, init_range=0.1, force_python=force_python)
    ids = np.array([1, 2, 3], np.int64)
    t.push(ids, np.ones((3, 3), np.float32), lr=0.1)
    ref = t.pull(ids)
    p = str(tmp_path / "table.bin")
    t.save(p)
    t2 = SparseTable(3, init_range=0.1, force_python=force_python)
    t2.load(p)
    np.testing.assert_allclose(t2.pull(ids), ref)
    assert t2.rows() == 3


# ---------------------------------------------------------------------------
# TCP service: 2 "pservers" on localhost (reference _run_cluster pattern)
# ---------------------------------------------------------------------------


@pytest.fixture
def two_servers():
    servers = [PSServer({0: SparseTable(4, init_range=0.0, seed=1)}).start()
               for _ in range(2)]
    client = PSClient([s.endpoint for s in servers])
    yield client
    client.stop_servers()
    client.close()
    for s in servers:
        s.stop()


def test_ps_pull_push_sharded(two_servers):
    client = two_servers
    ids = np.arange(20, dtype=np.int64)
    vals = client.pull(0, ids, 4)
    np.testing.assert_allclose(vals, 0.0)
    grads = np.ones((20, 4), np.float32)
    client.push(0, ids, grads, 4, lr=0.25)
    out = client.pull(0, ids, 4)
    np.testing.assert_allclose(out, -0.25)
    # rows spread over both shards, none lost
    assert client.rows(0) == 20


def test_ps_merge_add_and_save(two_servers, tmp_path):
    client = two_servers
    ids = np.array([1, 2, 3, 4], np.int64)
    client.merge_add(0, ids, np.full((4, 4), 2.0, np.float32), 4)
    np.testing.assert_allclose(client.pull(0, ids, 4), 2.0)
    client.save(0, str(tmp_path / "ps"))
    import glob

    assert len(glob.glob(str(tmp_path / "ps.shard*"))) == 2


# ---------------------------------------------------------------------------
# communicators
# ---------------------------------------------------------------------------


def test_async_communicator_flush(two_servers):
    client = two_servers
    comm = AsyncCommunicator(client, dim=4, lr=0.5).start()
    ids = np.array([5, 5, 6], np.int64)
    grads = np.ones((3, 4), np.float32)
    comm.push_sparse_grad(ids, grads)
    comm.flush()
    comm.stop()
    out = client.pull(0, np.array([5, 6], np.int64), 4)
    # dup id 5 merged (sum) then one SGD step: -0.5*2 and -0.5*1
    np.testing.assert_allclose(out[0], -1.0)
    np.testing.assert_allclose(out[1], -0.5)


def test_geo_communicator_sync(two_servers):
    client = two_servers
    local = SparseTable(4, init_range=0.0, seed=1, force_python=True)
    geo = GeoCommunicator(client, local, k_steps=2)
    ids = np.array([9, 10], np.int64)
    geo.snapshot(ids)
    local.push(ids, np.ones((2, 4), np.float32), lr=1.0)  # local -1 delta
    geo.step()          # step 1: no sync yet
    assert client.pull(0, ids, 4).max() == 0.0
    geo.step()          # step 2: delta sent, params merged back
    np.testing.assert_allclose(client.pull(0, ids, 4), -1.0)
    np.testing.assert_allclose(local.pull(ids), -1.0)


# ---------------------------------------------------------------------------
# sparse embedding end-to-end (CTR-style: DownpourWorker cycle)
# ---------------------------------------------------------------------------


def test_sparse_embedding_trains():
    paddle.seed(0)
    from paddle_tpu import nn

    emb = SparseEmbedding(8, optimizer="sgd", init_range=0.01, seed=2)
    fc = nn.Linear(8, 1)
    rng = np.random.RandomState(0)
    ids_all = rng.randint(0, 50, (200,)).astype(np.int64)
    y_all = (ids_all % 2).astype(np.float32)   # parity of the id

    losses = []
    for step in range(30):
        sel = rng.randint(0, 200, (32,))
        ids = ids_all[sel]
        y = paddle.to_tensor(y_all[sel].reshape(-1, 1))
        e = emb(paddle.to_tensor(ids))
        logit = fc(e)
        loss = ((logit - y) ** 2).mean()
        loss.backward()
        emb.push_gradients(lr=0.5)
        for p in fc.parameters():
            p._value = p._value - 0.1 * p.grad.value
            p.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]
    assert emb._table.rows() <= 50


def test_heartbeat_monitor_marks_dead_and_completed():
    from paddle_tpu.ps.heartbeat import COMPLETED, HeartBeatMonitor

    dead = []
    m = HeartBeatMonitor(num_trainers=2, timeout_s=0.2,
                         check_interval_s=0.05, on_dead=dead.append)
    m.start()
    try:
        m.update(0)
        m.update(1)
        assert m.alive(0) and m.alive(1)
        # trainer 1 completes, trainer 0 goes silent
        m.update(1, COMPLETED)
        time.sleep(0.6)
        assert m.dead_trainers() == [0]
        assert dead == [0]
        assert not m.alive(0)
        assert m.alive(1)  # completed trainers are never "dead"
        assert m.completed_trainers() == [1]
        # a late beat revives the trainer (rejoin)
        m.update(0)
        assert m.alive(0) and m.dead_trainers() == []
    finally:
        m.stop()


def test_heartbeat_over_ps_service():
    from paddle_tpu.ps.heartbeat import COMPLETED
    from paddle_tpu.ps.service import PSClient, PSServer
    from paddle_tpu.ps.table import SparseTable

    srv = PSServer({0: SparseTable(dim=4)}, num_trainers=2,
                   heartbeat_timeout_s=0.3)
    srv.monitor._interval = 0.05  # fast checks for the test
    srv.start()
    client = PSClient([srv.endpoint])
    try:
        client.heartbeat(trainer_id=0)
        client.heartbeat(trainer_id=1)
        assert srv.monitor.alive(0) and srv.monitor.alive(1)
        client.heartbeat(trainer_id=1, status=COMPLETED)
        time.sleep(0.8)
        assert srv.monitor.dead_trainers() == [0]
        assert srv.monitor.alive(1)
        assert not srv.monitor.all_completed()
        client.heartbeat(trainer_id=0, status=COMPLETED)
        assert srv.monitor.all_completed()
    finally:
        client.stop_servers()
        client.close()
        srv.stop()


def test_client_background_heartbeat():
    from paddle_tpu.ps.service import PSClient, PSServer
    from paddle_tpu.ps.table import SparseTable

    srv = PSServer({0: SparseTable(dim=4)}, num_trainers=1,
                   heartbeat_timeout_s=5.0).start()
    client = PSClient([srv.endpoint])
    try:
        client.start_heartbeat(trainer_id=0, interval_s=0.05)
        time.sleep(0.2)
        assert srv.monitor.alive(0)
        client.stop_heartbeat(trainer_id=0)
        assert srv.monitor.completed_trainers() == [0]
    finally:
        client.stop_servers()
        client.close()
        srv.stop()


def test_distribute_transpiler_roles(tmp_path):
    """DistributeTranspiler facade (reference distribute_transpiler.py:256):
    transpile a program with an embedding, boot the pserver plan, pull
    from a trainer-side client."""
    import paddle_tpu.static as static
    from paddle_tpu.distributed import (DistributeTranspiler,
                                        DistributeTranspilerConfig)
    from paddle_tpu.ps.service import PSClient

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = static.data("ids", [-1], dtype="int64")
        emb = static.embedding(ids, size=[100, 8])
        static.mean(emb)

    t = DistributeTranspiler(DistributeTranspilerConfig())
    t.transpile(trainer_id=0, program=main,
                pservers="127.0.0.1:0", trainers=1)
    assert t.get_trainer_program() is main
    plan = t.get_pserver_program("127.0.0.1:0")
    assert plan.tables == {0: (100, 8)}
    srv = plan.run()
    client = None
    try:
        client = PSClient([srv.endpoint])
        vals = client.pull(0, np.array([1, 2, 3], np.int64), dim=8)
        assert vals.shape == (3, 8)
    finally:
        if client is not None:
            client.stop_servers()
            client.close()
        plan.stop()


def test_transpiler_warns_on_dense_sends():
    """A program whose dense params carry in-program optimizer updates
    relied on the reference's server-side dense aggregation
    (distribute_transpiler.py:1678 _init_splited_vars); transpiling it
    for >1 trainers must WARN that dense state stays trainer-side here
    (VERDICT r4 weak #7) — and stay silent for the sparse-only shape."""
    import warnings

    import paddle_tpu.static as static
    from paddle_tpu.distributed import DistributeTranspiler
    from paddle_tpu.static.ir import OpDesc

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 8], dtype="float32")
        y = static.layers.fc(x, size=2)
        static.mean(y)
    # hand-append a dense sgd update (what append_backward+minimize
    # produce) so the program matches the reference's transpile input
    wname = next(n for n, v in main.global_block.vars.items()
                 if v.persistable and len(v.shape) == 2)
    main.global_block.ops.append(OpDesc(
        "sgd", {"Param": [wname], "Grad": [f"{wname}@GRAD"],
                "LearningRate": ["lr"]}, {"ParamOut": [wname]}, {}))

    t = DistributeTranspiler()
    with pytest.warns(RuntimeWarning, match="dense parameters ON THE "
                                            "TRAINERS"):
        t.transpile(trainer_id=0, program=main,
                    pservers="127.0.0.1:0", trainers=2)

    # sparse-only program, or a single trainer: no warning
    main2, startup2 = static.Program(), static.Program()
    with static.program_guard(main2, startup2):
        ids = static.data("ids", [-1], dtype="int64")
        emb = static.embedding(ids, size=[50, 4])
        static.mean(emb)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        DistributeTranspiler().transpile(
            trainer_id=0, program=main2, pservers="127.0.0.1:0",
            trainers=2)
        DistributeTranspiler().transpile(
            trainer_id=0, program=main, pservers="127.0.0.1:0",
            trainers=1)


def test_transpiler_validates_inputs():
    from paddle_tpu.distributed import DistributeTranspiler

    t = DistributeTranspiler()
    with pytest.raises(RuntimeError):
        t.get_trainer_program()
    import paddle_tpu.static as static
    main = static.Program()
    with pytest.raises(ValueError):
        t.transpile(0, program=main, pservers="", trainers=1)
    t.transpile(0, program=main, pservers="127.0.0.1:7164", trainers=2)
    with pytest.raises(ValueError):
        t.get_pserver_program("127.0.0.1:9999")
