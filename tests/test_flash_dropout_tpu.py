"""In-kernel dropout flash attention — TPU-only checks (the Pallas PRNG
has no CPU interpreter path; tests/conftest.py forces CPU, so this file
self-gates and is exercised by running pytest with the default TPU env:
`PYTHONPATH=/root/repo python -m pytest tests/test_flash_dropout_tpu.py`).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas TPU PRNG kernel needs a real TPU backend")


def _arrs(rng, B, L, H, D):
    return (jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
            for _ in range(3))


def test_dropout_statistics_and_determinism():
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas, _flash_attention_pallas_dropout)
    rng = np.random.RandomState(0)
    q, k, v = _arrs(rng, 2, 128, 2, 64)
    base = _flash_attention_pallas(q, k, v)
    outs = [_flash_attention_pallas_dropout(
        q, k, v, jnp.asarray([[s]], jnp.int32), 0.1) for s in range(32)]
    mean = jnp.mean(jnp.stack(outs), axis=0)
    rel = float(jnp.abs(mean - base).mean() / jnp.abs(base).mean())
    assert rel < 0.08, rel
    seed = jnp.asarray([[11]], jnp.int32)
    a = _flash_attention_pallas_dropout(q, k, v, seed, 0.1)
    b = _flash_attention_pallas_dropout(q, k, v, seed, 0.1)
    c = _flash_attention_pallas_dropout(q, k, v, seed + 1, 0.1)
    assert bool(jnp.all(a == b)) and bool(jnp.any(a != c))


def test_dropout_fraction_exact():
    """With q=0 probs are uniform, so dropped entries of the recovered
    probability matrix are exactly zero; their fraction ~ dropout_p."""
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas_dropout)
    rng = np.random.RandomState(1)
    B, L, H, D = 1, 128, 1, 64
    q = jnp.zeros((B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    seed = jnp.asarray([[5]], jnp.int32)
    pd = 0.25
    probs = np.zeros((L, L), np.float32)
    for blk in range(2):
        v = np.zeros((B, L, H, D), np.float32)
        for d in range(64):
            v[0, blk * 64 + d, 0, d] = 1.0
        out = _flash_attention_pallas_dropout(q, k, jnp.asarray(v), seed, pd)
        probs[:, blk * 64:(blk + 1) * 64] = np.asarray(out[0, :, 0, :])
    frac = float((probs == 0).mean())
    assert abs(frac - pd) < 0.03, frac


@pytest.mark.parametrize("L,causal", [(128, False), (512, True)])
def test_dropout_grads_directional(L, causal):
    """Directional derivative check; the keep mask is a pure function of
    (seed, tile), so f is smooth in q/k/v. Random cotangent weighting
    keeps the check sensitive (see optimization_barrier note in the bwd)."""
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas_dropout)
    rng = np.random.RandomState(2)
    q, k, v = _arrs(rng, 2, L, 2, 64)
    do = jnp.asarray(rng.randn(*q.shape), jnp.float32)
    seed = jnp.asarray([[9]], jnp.int32)
    pd = 0.2

    for name, fn, arr, t in [
        ("dq", lambda a: jnp.sum(_flash_attention_pallas_dropout(
            a, k, v, seed, pd, causal=causal) * do), q, 0.01),
        ("dk", lambda a: jnp.sum(_flash_attention_pallas_dropout(
            q, a, v, seed, pd, causal=causal) * do), k, 0.01),
        ("dv", lambda a: jnp.sum(_flash_attention_pallas_dropout(
            q, k, a, seed, pd, causal=causal) * do), v, 1.0),
    ]:
        g = jax.grad(fn)(arr)
        d = jnp.asarray(rng.randn(*arr.shape), jnp.float32)
        num = (float(fn(arr + t * d)) - float(fn(arr - t * d))) / (2 * t)
        ana = float(jnp.sum(g * d))
        assert abs(ana - num) / max(abs(num), 1e-6) < 0.05, (name, ana, num)


def test_dropout_constant_cotangent():
    """grad of plain sum(out): the cotangent is a broadcast constant —
    regression test for the Mosaic constant-folding mis-lowering that the
    optimization_barrier in the dropout bwd guards against."""
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_pallas_dropout)
    rng = np.random.RandomState(3)
    q, k, v = _arrs(rng, 2, 128, 2, 64)
    seed = jnp.asarray([[21]], jnp.int32)
    fn = lambda a: jnp.sum(_flash_attention_pallas_dropout(q, k, a, seed, 0.2))
    g = jax.grad(fn)(v)
    d = jnp.asarray(rng.randn(*v.shape), jnp.float32)
    num = (float(fn(v + d)) - float(fn(v - d))) / 2.0   # linear in v
    ana = float(jnp.sum(g * d))
    assert abs(ana - num) / max(abs(num), 1e-6) < 0.05, (ana, num)
