"""Numeric gradient checks for the long-tail differentiable ops — the
eager counterpart of the OpTest check_grad fixture (reference
op_test.py:57 get_numeric_gradient, delta=0.005): analytic jax.grad vs
central finite differences on the raw jnp implementations."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.crf import linear_chain_crf
from paddle_tpu import ops

pytestmark = pytest.mark.slow

DELTA = 5e-3
RTOL, ATOL = 5e-2, 5e-3


def _num_grad(f, args, idx, delta=DELTA):
    a = np.asarray(args[idx], np.float32)
    g = np.zeros_like(a)
    flat = a.ravel()
    for i in range(flat.size):
        for sign in (+1, -1):
            pert = flat.copy()
            pert[i] += sign * delta
            new = list(args)
            new[idx] = pert.reshape(a.shape)
            val = float(f(*new))
            g.ravel()[i] += sign * val / (2 * delta)
    return g


def _check(f, args, wrt):
    """f: scalar-valued fn of numpy arrays (first len(args) positional)."""
    jf = lambda *xs: f(*xs)
    for idx in wrt:
        analytic = np.asarray(
            jax.grad(jf, argnums=idx)(*[jnp.asarray(a) for a in args]))
        numeric = _num_grad(lambda *xs: jf(*[jnp.asarray(x) for x in xs]),
                            args, idx)
        np.testing.assert_allclose(analytic, numeric, rtol=RTOL, atol=ATOL)


RNG = np.random.RandomState(0)


def test_dice_loss_grad():
    x = jax.nn.softmax(jnp.asarray(RNG.randn(4, 3), jnp.float32))
    label = RNG.randint(0, 3, (4, 1)).astype(np.int64)
    _check(lambda p: jnp.sum(F.dice_loss.raw_fn(p, jnp.asarray(label))),
           [np.asarray(x)], [0])


def test_bpr_and_rank_losses_grad():
    x = RNG.randn(4, 5).astype(np.float32)
    lbl = RNG.randint(0, 5, (4, 1)).astype(np.int64)
    _check(lambda a: jnp.sum(F.bpr_loss.raw_fn(a, jnp.asarray(lbl))),
           [x], [0])
    label = RNG.rand(3, 1).astype(np.float32)
    left = RNG.randn(3, 1).astype(np.float32)
    right = RNG.randn(3, 1).astype(np.float32)
    _check(lambda l, r: jnp.sum(F.rank_loss.raw_fn(jnp.asarray(label),
                                                   l, r)),
           [left, right], [0, 1])
    _check(lambda l, r: jnp.sum(F.margin_rank_loss.raw_fn(
        jnp.asarray(label), l, r, margin=0.3)), [left, right], [0, 1])


def test_center_loss_grad():
    x = RNG.randn(4, 6).astype(np.float32)
    centers = RNG.randn(3, 6).astype(np.float32)
    lbl = RNG.randint(0, 3, (4,)).astype(np.int64)
    _check(lambda a, c: jnp.sum(F.center_loss.raw_fn(
        a, jnp.asarray(lbl), c)), [x, centers], [0, 1])


def test_bilinear_tensor_product_grad():
    x = RNG.randn(3, 4).astype(np.float32)
    y = RNG.randn(3, 5).astype(np.float32)
    w = (RNG.randn(2, 4, 5) * 0.3).astype(np.float32)
    _check(lambda a, b, ww: jnp.sum(jnp.square(
        F.bilinear_tensor_product_fn.raw_fn(a, b, ww))),
        [x, y, w], [0, 1, 2])


def test_affine_channel_and_row_conv_grad():
    x = RNG.randn(2, 3, 2, 2).astype(np.float32)
    s = RNG.randn(3).astype(np.float32)
    b = RNG.randn(3).astype(np.float32)
    _check(lambda a, sc, bb: jnp.sum(jnp.square(
        F.affine_channel.raw_fn(a, sc, bb))), [x, s, b], [0, 1, 2])
    seq = RNG.randn(2, 5, 3).astype(np.float32)
    w = RNG.randn(2, 3).astype(np.float32)
    _check(lambda a, ww: jnp.sum(jnp.square(F.row_conv.raw_fn(a, ww))),
           [seq, w], [0, 1])


def test_cos_sim_and_clip_by_norm_grad():
    x = RNG.randn(3, 4).astype(np.float32)
    y = RNG.randn(3, 4).astype(np.float32)
    _check(lambda a, b: jnp.sum(ops.math.cos_sim.raw_fn(a, b)),
           [x, y], [0, 1])
    big = (RNG.randn(4) * 3).astype(np.float32)
    _check(lambda a: jnp.sum(jnp.square(
        ops.math.clip_by_norm.raw_fn(a, 1.5))), [big], [0])


def test_soft_relu_brelu_grad():
    x = RNG.randn(8).astype(np.float32)
    _check(lambda a: jnp.sum(F.soft_relu.raw_fn(a)), [x], [0])
    # brelu is piecewise-linear; keep clear of the kinks
    x2 = np.array([-2.0, 1.0, 5.0, 30.0], np.float32)
    _check(lambda a: jnp.sum(F.brelu.raw_fn(a, 0.5, 24.0)), [x2], [0])


def test_linear_chain_crf_grad():
    B, L, T = 2, 3, 3
    em = RNG.randn(B, L, T).astype(np.float32)
    tr = (RNG.randn(T + 2, T) * 0.5).astype(np.float32)
    label = RNG.randint(0, T, (B, L)).astype(np.int64)
    lens = np.array([3, 2], np.int64)
    _check(lambda e, t: -jnp.sum(linear_chain_crf.raw_fn(
        e, t, jnp.asarray(label), jnp.asarray(lens))),
        [em, tr], [0, 1])


def test_teacher_student_loss_grad():
    x = RNG.randn(4, 1).astype(np.float32)
    lbl = RNG.rand(4, 1).astype(np.float32)
    _check(lambda a: jnp.sum(
        F.teacher_student_sigmoid_loss.raw_fn(a, jnp.asarray(lbl))),
        [x], [0])
