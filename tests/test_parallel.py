"""Sequence/context parallelism tests on the virtual 8-device CPU mesh
(SURVEY §4: multi-host logic tests via xla_force_host_platform_device_count).

Numerical ground truth is the single-device XLA attention; ring/Ulysses
sharded over 4 sequence shards must match it closely (f32 accumulation).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import _xla_attention
from paddle_tpu.parallel import (
    create_mesh, ring_attention, sequence_parallel, set_mesh,
)
from paddle_tpu.parallel.mesh import _global_mesh


@pytest.fixture
def mesh_dp2_sp4():
    mesh = create_mesh({"dp": 2, "sp": 4})
    prev = _global_mesh[0]
    set_mesh(mesh)
    yield mesh
    _global_mesh[0] = prev


def _qkv(b=2, l=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh_dp2_sp4, causal):
    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh_dp2_sp4, causal):
    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=causal,
                         impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match(mesh_dp2_sp4):
    q, k, v = _qkv(l=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                      is_causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, 0.0, True, None) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_sequence_parallel_context_routes_sdpa(mesh_dp2_sp4):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, False, None)
    qt, kt, vt = (paddle.to_tensor(np.asarray(x)) for x in (q, k, v))
    with sequence_parallel("sp"):
        out = F.scaled_dot_product_attention(qt, kt, vt)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_cross_alignment(mesh_dp2_sp4):
    """Causal cross-attention (lq != lk) must match the fallback's
    bottom-right alignment (tril k=kl-ql)."""
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 4, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 32, 4, 8), jnp.float32)
    ref = _xla_attention(q, k, v, None, 0.0, True, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_under_jit_and_grad(mesh_dp2_sp4):
    """ring attention composes with jit + value_and_grad (training path)."""
    q, k, v = _qkv(l=16)

    @jax.jit
    def step(q, k, v):
        def f(q):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                          is_causal=False))
        return jax.value_and_grad(f)(q)

    val, g = step(q, k, v)
    assert np.isfinite(float(val))
    assert g.shape == q.shape
