"""Sequence/context parallelism tests on the virtual 8-device CPU mesh
(SURVEY §4: multi-host logic tests via xla_force_host_platform_device_count).

Numerical ground truth is the single-device XLA attention; ring/Ulysses
sharded over 4 sequence shards must match it closely (f32 accumulation).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import _xla_attention
from paddle_tpu.parallel import (
    create_mesh, ring_attention, sequence_parallel, set_mesh,
)
from paddle_tpu.parallel.mesh import _global_mesh


pytestmark = pytest.mark.slow

@pytest.fixture
def mesh_dp2_sp4():
    mesh = create_mesh({"dp": 2, "sp": 4})
    prev = _global_mesh[0]
    set_mesh(mesh)
    yield mesh
    _global_mesh[0] = prev


def _qkv(b=2, l=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh_dp2_sp4, causal):
    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh_dp2_sp4, causal):
    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=causal,
                         impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match(mesh_dp2_sp4):
    q, k, v = _qkv(l=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                      is_causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, 0.0, True, None) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_sequence_parallel_context_routes_sdpa(mesh_dp2_sp4):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    q, k, v = _qkv()
    ref = _xla_attention(q, k, v, None, 0.0, False, None)
    qt, kt, vt = (paddle.to_tensor(np.asarray(x)) for x in (q, k, v))
    with sequence_parallel("sp"):
        out = F.scaled_dot_product_attention(qt, kt, vt)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_cross_alignment(mesh_dp2_sp4):
    """Causal cross-attention (lq != lk) must match the fallback's
    bottom-right alignment (tril k=kl-ql)."""
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 4, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 32, 4, 8), jnp.float32)
    ref = _xla_attention(q, k, v, None, 0.0, True, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_under_jit_and_grad(mesh_dp2_sp4):
    """ring attention composes with jit + value_and_grad (training path)."""
    q, k, v = _qkv(l=16)

    @jax.jit
    def step(q, k, v):
        def f(q):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                          is_causal=False))
        return jax.value_and_grad(f)(q)

    val, g = step(q, k, v)
    assert np.isfinite(float(val))
    assert g.shape == q.shape


# ---------------------------------------------------------------------------
# masked ring attention (VERDICT r1 item 9: key-padding masks must ride the
# ring at block granularity, not silently fall back to replicated attention)
# ---------------------------------------------------------------------------


def _padding_mask(b, l, lengths):
    m = np.zeros((b, l), bool)
    for i, n in enumerate(lengths):
        m[i, :n] = True
    return jnp.asarray(m)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_masked_ring_matches_reference(mesh_dp2_sp4, causal, impl):
    """Key-padding masks sharded over sp must reproduce the single-device
    masked attention, including blocks that are entirely padding (batch
    row 0 has 8 valid keys -> sp shards 2-4 see all-padded blocks)."""
    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    mask = _padding_mask(b, l, [8, 29])
    ref = _xla_attention(q, k, v, mask[:, None, None, :], 0.0, causal, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=causal,
                         impl=impl, kv_mask=mask)
    for i in range(b):
        np.testing.assert_allclose(
            np.asarray(out)[i], np.asarray(ref)[i], atol=2e-5)


def test_masked_ring_fully_masked_rows_zero(mesh_dp2_sp4):
    """Rows whose every key is padded yield zeros (not NaN) in the ring
    path; the XLA softmax would give mean-of-V garbage instead."""
    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    mask = jnp.zeros((b, l), bool).at[1, :16].set(True)  # row 0 all pad
    out = np.asarray(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                    kv_mask=mask))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[0], 0.0, atol=1e-6)
    ref = _xla_attention(q[1:], k[1:, :16], v[1:, :16], None, 0.0, False,
                         None)
    np.testing.assert_allclose(out[1], np.asarray(ref)[0], atol=2e-5)


def test_masked_ring_grads_match(mesh_dp2_sp4):
    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    mask = _padding_mask(b, l, [24, 32])

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                      kv_mask=mask) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(
            q, k, v, mask[:, None, None, :], 0.0, False, None) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=3e-5)


def test_sdpa_padding_mask_routes_to_ring(mesh_dp2_sp4):
    """scaled_dot_product_attention with a key-padding mask inside a
    sequence_parallel scope takes the ring path (no fallback warning) and
    matches the reference; a query-dependent mask warns and falls back."""
    import warnings

    from paddle_tpu.nn import functional as F

    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    mask = _padding_mask(b, l, [24, 32])
    ref = _xla_attention(q, k, v, mask[:, None, None, :], 0.0, False, None)
    with sequence_parallel(mesh=mesh_dp2_sp4):
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, training=False)
    np.testing.assert_allclose(np.asarray(out.numpy()), np.asarray(ref),
                               atol=2e-5)

    # a concrete causal mask decomposes onto the native ring path now
    qmask = jnp.tril(jnp.ones((b, 1, l, l), bool))
    cref = _xla_attention(q, k, v, None, 0.0, True, None)
    with sequence_parallel(mesh=mesh_dp2_sp4):
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            cout = F.scaled_dot_product_attention(
                q, k, v, attn_mask=qmask, training=False)
    np.testing.assert_allclose(np.asarray(cout.numpy()), np.asarray(cref),
                               atol=2e-5)


def test_sdpa_causal_plus_padding_mask_decomposes(mesh_dp2_sp4):
    """The standard training mask — bottom-right causal tril AND key
    padding, materialized as one (B, 1, L, L) bool array — must ride the
    ring natively (VERDICT r2 weak #5), matching single-device XLA."""
    import warnings

    from paddle_tpu.nn import functional as F

    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    pad = np.asarray(_padding_mask(b, l, [24, 32]))
    full = np.tril(np.ones((l, l), bool))[None] & pad[:, None, :]
    ref = _xla_attention(q, k, v, jnp.asarray(full[:, None]), 0.0, False,
                         None)
    with sequence_parallel(mesh=mesh_dp2_sp4):
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=jnp.asarray(full[:, None]),
                training=False)
    np.testing.assert_allclose(np.asarray(out.numpy()), np.asarray(ref),
                               atol=2e-5)


def test_sdpa_undecomposable_mask_raises_unless_opted_in(mesh_dp2_sp4):
    """Masks the ring genuinely cannot carry raise with guidance; the
    FLAGS_sp_mask_fallback escape hatch restores the old warn+replicate
    behavior."""
    from paddle_tpu.framework.flags import get_flag, set_flags
    from paddle_tpu.nn import functional as F

    b, l = 2, 32
    q, k, v = _qkv(b=b, l=l)
    rng = np.random.RandomState(3)
    arbitrary = jnp.asarray(rng.rand(b, 1, l, l) > 0.5)
    with sequence_parallel(mesh=mesh_dp2_sp4):
        with pytest.raises(ValueError, match="query-dependent"):
            F.scaled_dot_product_attention(q, k, v, attn_mask=arbitrary,
                                           training=False)
    prev = get_flag("sp_mask_fallback")
    set_flags({"sp_mask_fallback": True})
    try:
        with sequence_parallel(mesh=mesh_dp2_sp4):
            with pytest.warns(RuntimeWarning, match="fell back"):
                out = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=arbitrary, training=False)
        ref = _xla_attention(q, k, v, arbitrary, 0.0, False, None)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(ref), atol=2e-5)
    finally:
        set_flags({"sp_mask_fallback": prev})


def test_ring_causal_block_skip_long_seq_parity(mesh_dp2_sp4):
    """Causal block-skipping (KV blocks above the diagonal skipped via
    lax.cond) must not change numerics — longer sequence so every skip
    branch is exercised, plus combined causal+padding."""
    b, l = 2, 64
    q, k, v = _qkv(b=b, l=l, seed=11)
    mask = _padding_mask(b, l, [40, 64])
    ref = _xla_attention(q, k, v, mask[:, None, None, :], 0.0, True, None)
    out = ring_attention(q, k, v, mesh=mesh_dp2_sp4, is_causal=True,
                         kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh_dp2_sp4,
                                      is_causal=True, kv_mask=mask) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(
            q, k, v, mask[:, None, None, :], 0.0, True, None) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_bf16_inputs(mesh_dp2_sp4, causal):
    """bf16 q/k/v keep the MXU einsums in bf16 (2x throughput under AMP)
    while softmax stats/accumulator stay f32 — output must track the f32
    reference within bf16 tolerance and come back as bf16."""
    q, k, v = _qkv(l=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    out = ring_attention(qb, kb, vb, mesh=mesh_dp2_sp4, is_causal=causal)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)
