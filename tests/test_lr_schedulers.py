"""LR schedule closed forms (reference layers/learning_rate_scheduler.py
decay family)."""
def test_natural_exp_and_inverse_time_decay():
    """learning_rate_scheduler.py natural_exp_decay / inverse_time_decay
    closed forms (reference layers/learning_rate_scheduler.py)."""
    import math

    from paddle_tpu.optimizer import lr

    s = lr.natural_exp_decay(0.1, 10, 0.5)
    for _ in range(20):
        s.step()
    assert abs(s() - 0.1 * math.exp(-0.5 * 2.0)) < 1e-9

    s = lr.natural_exp_decay(0.1, 10, 0.5, staircase=True)
    for _ in range(15):
        s.step()
    assert abs(s() - 0.1 * math.exp(-0.5 * 1.0)) < 1e-9

    s = lr.inverse_time_decay(0.1, 10, 0.5)
    for _ in range(20):
        s.step()
    assert abs(s() - 0.1 / (1 + 0.5 * 2.0)) < 1e-9


def test_exponential_decay_staircase():
    from paddle_tpu.optimizer import lr

    s = lr.exponential_decay(0.2, 10, 0.5, staircase=True)
    for _ in range(25):
        s.step()
    assert abs(s() - 0.2 * 0.5 ** 2) < 1e-9
