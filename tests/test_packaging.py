"""Packaging + native-code sanitizer smoke (§2.8 tooling gaps).

- wheel build: the sdist/wheel pipeline must produce an installable
  artifact carrying the native sources (reference setup.py.in wheel
  flow). Gated on setuptools availability; builds in-process without
  touching the environment.
- ASAN: the native MultiSlot parser runs a load/iterate cycle under
  AddressSanitizer as a standalone binary (reference WITH_ASAN CI
  toggle). Gated on the toolchain supporting -fsanitize=address.
"""
import os
import subprocess
import sys
import zipfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_wheel_builds_and_carries_native_sources(tmp_path):
    try:
        import setuptools  # noqa: F401
        from setuptools import build_meta  # noqa: F401
    except ImportError:
        pytest.skip("setuptools unavailable")
    out = subprocess.run(
        [sys.executable, "-c",
         "from setuptools import build_meta as b; import sys; "
         f"print(b.build_wheel({str(tmp_path)!r}))"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    wheel = out.stdout.strip().splitlines()[-1]
    path = tmp_path / wheel
    assert path.exists()
    names = zipfile.ZipFile(path).namelist()
    assert any(n.endswith("native/src/datafeed.cc") for n in names), names
    assert any(n.endswith("native/include/paddle_tpu_capi.h")
               for n in names), names
    assert any(n.endswith("models/bert.py") for n in names)
    # build/ artifacts (content-hash .so cache) must not leak into wheels
    assert not any("/build/" in n and n.endswith(".so") for n in names)


_ASAN_DRIVER = r"""
#include <cstdio>
extern "C" {
  void* pt_dataset_new(const char* types);
  long long pt_dataset_load_file(void* h, const char* path, int threads);
  void pt_dataset_start(void* h, long long batch, int drop_last);
  int pt_dataset_next(void* h);
  int pt_batch_rows(void* h);
  void pt_dataset_free(void* h);
}
int main(int argc, char** argv) {
  void* h = pt_dataset_new("ufu");
  long long n = pt_dataset_load_file(h, argv[1], 2);
  if (n <= 0) { std::printf("LOAD-FAIL\n"); return 1; }
  pt_dataset_start(h, 4, 0);
  int rows = 0;
  while (pt_dataset_next(h)) rows += pt_batch_rows(h);
  pt_dataset_free(h);
  std::printf("ROWS %d\n", rows);
  return rows == (int)n ? 0 : 2;
}
"""


@pytest.mark.slow
def test_native_datafeed_under_asan(tmp_path):
    src = os.path.join(REPO, "paddle_tpu", "native", "src", "datafeed.cc")
    probe = subprocess.run(
        ["g++", "-fsanitize=address", "-x", "c++", "-", "-o",
         str(tmp_path / "probe")],
        input="int main(){return 0;}", text=True, capture_output=True)
    if probe.returncode != 0:
        pytest.skip("toolchain lacks -fsanitize=address")

    driver = tmp_path / "driver.cc"
    driver.write_text(_ASAN_DRIVER)
    exe = tmp_path / "asan_feed"
    build = subprocess.run(
        ["g++", "-g", "-O1", "-std=c++17", "-fsanitize=address", "-pthread",
         src, str(driver), "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]

    data = tmp_path / "part.txt"
    lines = []
    for i in range(37):
        lines.append(f"2 {i} {i + 1} 2 0.5 -0.5 1 {i % 2}")
    data.write_text("\n".join(lines) + "\n")

    run = subprocess.run([str(exe), str(data)], capture_output=True,
                         text=True, timeout=120)
    assert run.returncode == 0, (run.stdout, run.stderr[-2000:])
    assert "ROWS 37" in run.stdout
    assert "AddressSanitizer" not in run.stderr
