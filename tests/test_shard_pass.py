"""GSPMD shard_propagation pass + DP×TP×PP compiled executor steps.

The correctness story mirrors the PR 3-5 pass gates, extended across
chips (the conftest forces an 8-virtual-device CPU topology):

- propagation unit rules: matmul column/row parallel (psum accounting on
  the contracted dim), elementwise pass-through/merge, conflict and
  reduction resolution by replication
- hint -> __sharding_spec stamp -> real NamedSharding round trip through
  the executor (state lands tp-partitioned on device)
- a DP×TP compiled step matches the single-chip run within the
  established gm tolerance (<= 1.2e-7) over >= 3 steps
- the escape hatches (PADDLE_IR_PASSES=0; absent hints/mesh) leave
  today's single-chip behavior bitwise intact
- hint/mesh flips can never hit a stale executable (content-key
  separation)
- pipeline_stages composes with gradient_merge_k into the GPipe schedule
  at parity with the plain gm scan, and the counters land in
  exe.counters
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import passes as passes_mod
from paddle_tpu.utils import unique_name

TOL = 1.2e-7   # the established gm tolerance (ISSUE 10 acceptance)


@pytest.fixture(autouse=True)
def _pin_env(monkeypatch):
    # an inherited escape hatch or amp override would silently turn a
    # leg into a different config
    for k in ("PADDLE_IR_PASSES", "PADDLE_AMP", "PADDLE_AMP_LEVEL"):
        monkeypatch.delenv(k, raising=False)


def _mlp(seed=1234, dropout=False):
    """3-layer fc net; returns (main, startup, loss, param_names) with
    params[0] 2-D (16, 32) and params[2] 2-D (32, 16) — the column/
    row-parallel hint targets."""
    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = seed
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 16])
        label = static.data("label", [-1, 1], dtype="int64")
        h = static.nn.fc(x, 32, act="relu")
        if dropout:
            h = static.dropout(h, dropout_prob=0.1)
        h = static.nn.fc(h, 16, act="relu")
        logits = static.nn.fc(h, 4)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label))
        static.SGD(0.05).minimize(loss)
    return main, startup, loss, [p.name for p in main.all_parameters()]


def _feed(b=8):
    rng = np.random.RandomState(3)
    return {"x": rng.randn(b, 16).astype(np.float32),
            "label": rng.randint(0, 4, (b, 1)).astype(np.int64)}


def _strategy(hints=None, mesh=None, k=1, pp=1):
    bs = static.BuildStrategy()
    if mesh:
        bs.mesh_shape = dict(mesh)
    if hints:
        bs.sharding_hints = dict(hints)
    bs.gradient_merge_k = k
    bs.pipeline_stages = pp
    return bs


def _run(strategy=None, steps=3, dropout=False, feed=None):
    """One fresh leg: fresh names, scope, executor (the executor folds
    its step counter into the RNG key — bitwise legs need parity of
    _step too)."""
    feed = feed or _feed()
    with unique_name.guard():
        scope = static.Scope()
        with static.scope_guard(scope):
            main, startup, loss, params = _mlp(dropout=dropout)
            exe = static.Executor()
            exe.run(startup)
            target = static.CompiledProgram(main, build_strategy=strategy) \
                if strategy is not None else main
            losses = [exe.run(target, feed=feed, fetch_list=[loss])[0]
                      for _ in range(steps)]
            return (np.concatenate([np.ravel(x) for x in losses]),
                    dict(exe.counters), scope, params)


# ---------------------------------------------------------------------------
# propagation unit rules (pass-level, no executor)
# ---------------------------------------------------------------------------
def _spec_of(program, name):
    v = program.global_block.vars.get(name)
    return passes_mod._spec_from_json(
        (getattr(v, "attrs", None) or {}).get("__sharding_spec"))


def test_matmul_col_row_parallel_rules():
    with unique_name.guard():
        main, _, loss, params = _mlp()
    bs = _strategy(hints={params[0]: (None, "tp"),
                          params[2]: ("tp", None)},
                   mesh={"dp": 2, "tp": 2})
    opt, report = static.apply_passes(main, ["x", "label"], [loss.name],
                                      bs)
    blk = opt.global_block
    # column-parallel: mul(x, w0) output rides (dp, tp)
    muls = [op for op in blk.ops if op.type == "mul"]
    assert _spec_of(opt, muls[0].outputs["Out"][0]) == ("dp", "tp")
    # row-parallel: contracted dim sharded -> psum stamped on the op
    row_mul = next(op for op in blk.ops
                   if op.type == "mul"
                   and op.inputs.get("Y") == [params[2]])
    assert row_mul.attrs.get("__psum_axes") == ["tp"]
    assert _spec_of(opt, row_mul.outputs["Out"][0]) == ("dp", None)
    # hints stamped verbatim on the params; grads inherit them
    assert _spec_of(opt, params[0]) == (None, "tp")
    assert _spec_of(opt, params[0] + "@GRAD") == (None, "tp")
    assert _spec_of(opt, params[2] + "@GRAD") == ("tp", None)
    # feeds ride the batch ('dp') axis by default
    assert _spec_of(opt, "x") == ("dp", None)
    assert report.shard["shard_psums_inserted"] >= 2  # row mul + dp loss
    assert report.shard["shard_vars_annotated"] > 4
    assert any(r["src"] == "hint" for r in report.shard_table)
    # the spec table is renderable (dump_passes --sharding face)
    assert params[0] in report.shard_spec_table()


def test_conflict_resolves_by_replication():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        a = static.data("a", [8, 16])
        b = static.data("b", [8, 16])
        out = static.elementwise_add(a, b)
    bs = _strategy(hints={"a": ("dp", None), "b": ("tp", None)},
                   mesh={"dp": 2, "tp": 2})
    opt, report = static.apply_passes(main, ["a", "b"], [out.name], bs)
    # dim0 disagrees (dp vs tp) -> replicated, counted
    assert _spec_of(opt, out.name) is None
    assert report.shard["shard_conflicts_replicated"] >= 1


def test_reduction_drops_sharded_dim_and_counts_psum():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 16])
        r = static.reduce_mean(x, dim=[1])
    bs = _strategy(hints={"x": (None, "tp")}, mesh={"tp": 2})
    opt, report = static.apply_passes(main, ["x"], [r.name], bs)
    # reducing the tp-sharded dim is a psum; the survivor is replicated
    assert _spec_of(opt, r.name) is None
    assert report.shard["shard_psums_inserted"] >= 1


def test_uneven_dims_and_unknown_axes_replicate():
    with unique_name.guard():
        main, _, loss, params = _mlp()
    # 'xx' is not a mesh axis; dim 32 % 3-sized axis would not divide
    bs = _strategy(hints={params[0]: (None, "xx")}, mesh={"dp": 2})
    opt, _ = static.apply_passes(main, ["x", "label"], [loss.name], bs)
    assert _spec_of(opt, params[0]) is None


def test_matmul_untracked_x_keeps_feature_axis_on_last_dim():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8])
        # transpose has no propagation rule: its output is untracked,
        # so the matmul sees a spec-less X
        t = static.transpose(x, perm=[1, 0])
        w = static.create_parameter([4, 6], "float32", name="w_tp")
        out = static.matmul(t, w)
    bs = _strategy(hints={"w_tp": (None, "tp")}, mesh={"dp": 2, "tp": 2})
    opt, _ = static.apply_passes(main, ["x"], [out.name], bs)
    # the column axis must stay on the LAST dim, not drift onto dim 0
    assert _spec_of(opt, out.name) == (None, "tp")


def test_pipeline_without_gm_is_a_clean_no_op():
    # pipeline_stages without gradient_merge_k > 1 has no microbatches:
    # no __pp_stage stamps (no content-hash flip), no pp_stages gauge,
    # and the run is bitwise the plain step
    base, _, _, _ = _run(steps=2)
    pp_only, counters, _, _ = _run(_strategy(pp=2), steps=2)
    assert pp_only.tobytes() == base.tobytes()
    assert "pp_stages" not in counters
    with unique_name.guard():
        main, _, loss, _ = _mlp()
    opt, report = static.apply_passes(main, ["x", "label"], [loss.name],
                                      _strategy(pp=2))
    assert not any("__pp_stage" in op.attrs
                   for op in opt.global_block.ops)
    assert "pp_stages" not in report.shard


def test_mesh_shape_wrong_type_raises_helpfully():
    bs = _strategy()
    bs.mesh_shape = "dp=2,tp=2"
    with pytest.raises(ValueError, match="mesh_shape"):
        passes_mod.resolve_sharding(bs)


def test_escape_hatch_resolves_none(monkeypatch):
    bs = _strategy(hints={"w": (None, "tp")}, mesh={"dp": 2, "tp": 2},
                   pp=2)
    assert passes_mod.resolve_sharding(bs) is not None
    assert passes_mod.resolve_pipeline(bs) == 2
    monkeypatch.setenv("PADDLE_IR_PASSES", "0")
    assert passes_mod.resolve_sharding(bs) is None
    assert passes_mod.resolve_pipeline(bs) is None


# ---------------------------------------------------------------------------
# executor legs (8 forced CPU devices from conftest)
# ---------------------------------------------------------------------------
def test_hint_to_namedsharding_round_trip():
    from jax.sharding import PartitionSpec as P

    with unique_name.guard():
        _, _, _, params = _mlp()
    hints = {params[0]: (None, "tp"), params[2]: ("tp", None)}
    _, counters, scope, params = _run(
        _strategy(hints=hints, mesh={"dp": 2, "tp": 2}))
    w0 = scope._peek(params[0])
    w2 = scope._peek(params[2])
    # out_shardings pin the written-back state to the hinted layout
    assert w0.sharding.spec == P(None, "tp"), w0.sharding
    assert w2.sharding.spec == P("tp", None), w2.sharding
    assert set(w0.sharding.mesh.axis_names) == {"dp", "tp"}
    # counters land in exe.counters
    assert counters["shard_vars_annotated"] > 0
    assert counters["shard_psums_inserted"] >= 1


def test_dp_tp_parity_vs_single_chip():
    single, _, _, params = _run(steps=3)
    hints = {params[0]: (None, "tp"), params[2]: ("tp", None)}
    sharded, _, _, _ = _run(
        _strategy(hints=hints, mesh={"dp": 2, "tp": 2}), steps=3)
    assert single.shape == sharded.shape
    delta = float(np.max(np.abs(single - sharded)))
    assert delta <= TOL, (delta, single, sharded)


def test_escape_hatch_and_no_hints_bitwise(monkeypatch):
    base, _, _, params = _run(steps=3, dropout=True)
    # default strategy (mesh_shape {} / no hints): bitwise = today
    nohints, _, _, _ = _run(_strategy(), steps=3, dropout=True)
    assert nohints.tobytes() == base.tobytes()
    # mesh+hints+pp strategy under the global escape must be bitwise
    # identical to a plain run under the same escape (one env flip
    # restores the whole single-chip baseline)
    hints = {params[0]: (None, "tp"), params[2]: ("tp", None)}
    monkeypatch.setenv("PADDLE_IR_PASSES", "0")
    escaped, _, _, _ = _run(
        _strategy(hints=hints, mesh={"dp": 2, "tp": 2}, k=4, pp=2),
        steps=3, dropout=True)
    plain_escape, _, _, _ = _run(steps=3, dropout=True)
    assert escaped.tobytes() == plain_escape.tobytes()


def test_cache_key_separation_on_hint_and_mesh_flip():
    feed = _feed()
    with unique_name.guard():
        scope = static.Scope()
        with static.scope_guard(scope):
            main, startup, loss, params = _mlp()
            exe = static.Executor()
            exe.run(startup)

            def go(bs):
                cp = static.CompiledProgram(main, build_strategy=bs)
                exe.run(cp, feed=feed, fetch_list=[loss])

            go(_strategy(hints={params[0]: (None, "tp")},
                         mesh={"dp": 2, "tp": 2}))
            misses1 = exe.counters["compile_cache_misses"]
            # hint flip -> new executable, never a stale hit
            go(_strategy(hints={params[0]: ("tp", None)},
                         mesh={"dp": 2, "tp": 2}))
            misses2 = exe.counters["compile_cache_misses"]
            assert misses2 == misses1 + 1
            # mesh flip -> new executable too
            go(_strategy(hints={params[0]: (None, "tp")},
                         mesh={"dp": 4}))
            assert exe.counters["compile_cache_misses"] == misses2 + 1
            # unchanged config -> pure cache hit
            hits = exe.counters.get("compile_cache_hits", 0)
            go(_strategy(hints={params[0]: (None, "tp")},
                         mesh={"dp": 4}))
            assert exe.counters["compile_cache_hits"] == hits + 1
            assert exe.counters["compile_cache_misses"] == misses2 + 1


def test_pipeline_schedule_parity_at_gm():
    # dropout on: the GPipe schedule derives each microbatch's RNG key
    # exactly like the gm scan (fold_in(rng, m)), so masks match
    gm, gmc, _, _ = _run(_strategy(k=4), steps=3, dropout=True)
    pp, ppc, _, _ = _run(_strategy(k=4, pp=2), steps=3, dropout=True)
    delta = float(np.max(np.abs(gm - pp)))
    assert delta <= TOL, (delta, gm, pp)
    assert ppc["pp_stages"] == 2
    # still one merged dispatch per step covering k microbatches
    assert ppc["gm_dispatches"] == 3
    assert ppc["gm_microbatches"] == 12
    assert "pp_stages" not in gmc or gmc["pp_stages"] == 0


def test_pipeline_composes_with_dp_tp():
    gm, _, _, params = _run(_strategy(k=4), steps=3)
    hints = {params[0]: (None, "tp"), params[2]: ("tp", None)}
    full, counters, _, _ = _run(
        _strategy(hints=hints, mesh={"dp": 2, "tp": 2}, k=4, pp=2),
        steps=3)
    delta = float(np.max(np.abs(gm - full)))
    assert delta <= TOL, (delta, gm, full)
    assert counters["pp_stages"] == 2
    assert counters["shard_psums_inserted"] >= 1


def test_train_from_dataset_stages_into_shard_layout():
    """The prefetch thread must stage batches into the SAME layout the
    AOT step's in_shardings expect — a plain (single-device) device_put
    of a batch would be rejected at dispatch."""
    batches = [_feed() for _ in range(3)]
    with unique_name.guard():
        scope = static.Scope()
        with static.scope_guard(scope):
            main, startup, loss, params = _mlp()
            exe = static.Executor()
            exe.run(startup)
            bs = _strategy(hints={params[0]: (None, "tp"),
                                  params[2]: ("tp", None)},
                           mesh={"dp": 2, "tp": 2})
            cp = static.CompiledProgram(main, build_strategy=bs)
            out = exe.train_from_dataset(cp, dataset=batches,
                                         fetch_list=[loss],
                                         print_period=1)
            assert out is not None and np.isfinite(np.ravel(out[0])[0])
            assert exe.counters["executor_steps"] == 3
            assert exe.counters["shard_psums_inserted"] >= 1


# ---------------------------------------------------------------------------
# satellites: generalized data_sharding + gpipe schedule helpers
# ---------------------------------------------------------------------------
def test_data_sharding_derives_axes_from_mesh():
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import data_sharding, mesh_for_shape

    mesh = mesh_for_shape({"dp": 2, "tp": 2})
    assert data_sharding(mesh).spec == P(("dp",))
    # explicit batch axes (e.g. batch rows over dp AND sp)
    mesh2 = mesh_for_shape({"dp": 2, "sp": 2})
    assert data_sharding(mesh2, axes=("dp", "sp")).spec == \
        P(("dp", "sp"))
    # absent names drop instead of erroring
    assert data_sharding(mesh, axes=("nope",)).spec == P(None)
    # classic CompiledProgram 'data' axis still derives by default
    mesh3 = mesh_for_shape({"data": 2})
    assert data_sharding(mesh3, batch_ndim=2).spec == P(("data",), None)


def test_gpipe_schedule_grid():
    from paddle_tpu.parallel import gpipe_bubble_fraction, gpipe_schedule

    ticks = list(gpipe_schedule(2, 4))
    assert len(ticks) == 5  # S + M - 1
    # every (stage, microbatch) pair runs exactly once, stage s at
    # tick s + m, stages descending within a tick
    seen = {}
    for t, pairs in ticks:
        assert [s for s, _ in pairs] == sorted(
            [s for s, _ in pairs], reverse=True)
        for s, m in pairs:
            assert 0 <= m < 4
            seen[(s, m)] = t
    assert len(seen) == 8
    for (s, m), t in seen.items():
        assert t == s + m
    assert gpipe_bubble_fraction(2, 4) == pytest.approx(1 / 5)
    assert gpipe_bubble_fraction(1, 4) == 0.0
