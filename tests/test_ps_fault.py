"""ISSUE 8: fault-tolerant parameter server — replica groups, shard-map
epochs, typed client errors, crash-safe shard recovery, and the
kill-a-primary chaos drill.

Everything here is tier-1 fast: in-process servers on loopback sockets,
fault injection via paddle_tpu.fault, fake clocks on every bounded wait
that matters, and real sleeps only for sub-second lease expiries. The
one subprocess test is the deterministic chaos drill
(tools/chaos_drill.py --ps as a library), whose wall clock is dominated
by two pserver imports."""
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.distributed.http_kv import KVClient, KVServer
from paddle_tpu.fault import injector as fault
from paddle_tpu.ps.replication import (
    DeltaLog, PSRequestError, PSUnavailable, ReplicaCoordinator,
    ReplicaDiverged, ReplicatedPSServer, ShardMap, ShardMapStale,
    _RawPeer, fetch_shard_map, local_digest, publish_shard_map,
    verify_replicas, wait_shard_map,
)
from paddle_tpu.ps.service import (
    ERR_BAD_REQUEST, OP_PUSH, PSClient, PSServer, _ERR_HDR, _HDR,
    _recv_exact,
)
from paddle_tpu.ps.table import SparseTable

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _counters():
    return profiler.counters_snapshot()


def _delta(before, name):
    return _counters().get(name, 0) - before.get(name, 0)


@pytest.fixture
def kv():
    srv = KVServer(_free_port())
    srv.start()
    client = KVClient(f"127.0.0.1:{srv.http_server.server_address[1]}")
    yield client
    srv.stop()


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.disarm_all()
    yield
    fault.disarm_all()


def _table(dim=4):
    return SparseTable(dim, init_range=0.0, seed=1)


def _mk_pair(kv, job="j", sync=True, lease_a=10.0, lease_b=10.0,
             snap_a=None, snap_b=None, snapshot_every=0):
    """Replicated 2-replica group: A primary + B backup, map published."""
    pa, pb = _free_port(), _free_port()
    coord = ReplicaCoordinator(kv, job=job, lease_ttl=min(lease_a, lease_b),
                               boot_grace=60.0)
    coord.publish([[f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"]], sync=sync)
    a = ReplicatedPSServer({0: _table()}, kv, job=job, port=pa,
                           lease_ttl=lease_a, snapshot_dir=snap_a,
                           snapshot_every=snapshot_every).start()
    b = ReplicatedPSServer({0: _table()}, kv, job=job, port=pb,
                           lease_ttl=lease_b, snapshot_dir=snap_b,
                           snapshot_every=snapshot_every).start()
    return coord, a, b


IDS = np.arange(20, dtype=np.int64)
ONES = np.ones((20, 4), np.float32)


# ---------------------------------------------------------------------------
# shard map
# ---------------------------------------------------------------------------

def test_shard_map_roundtrip_and_roles():
    m = ShardMap([["a:1", "b:1"], ["c:1"]], epoch=3, sync=False, job="x")
    m2 = ShardMap.from_json(m.to_json())
    assert m2.groups == m.groups and m2.epoch == 3 and not m2.sync
    assert m2.primary(0) == "a:1" and m2.backups(0) == ["b:1"]
    assert m2.role_of("b:1") == ("backup", 0)
    assert m2.role_of("c:1") == ("primary", 1)
    assert m2.role_of("zz:9") == (None, -1)
    with pytest.raises(ValueError):
        ShardMap([["a:1"]], epoch=0)       # epochs start at 1
    with pytest.raises(ValueError):
        ShardMap([[]])


def test_publish_fetch_epoch_ordering(kv):
    assert fetch_shard_map(kv, "j") is None
    publish_shard_map(kv, ShardMap([["a:1"]], epoch=1, job="j"))
    publish_shard_map(kv, ShardMap([["b:1"]], epoch=2, job="j"))
    m = fetch_shard_map(kv, "j")
    assert m.epoch == 2 and m.primary(0) == "b:1"


def test_wait_shard_map_timeout_typed(kv):
    t = [0.0]
    with pytest.raises(ShardMapStale) as ei:
        wait_shard_map(kv, "j", min_epoch=5, timeout=2.0,
                       clock=lambda: t[0],
                       sleep=lambda d: t.__setitem__(0, t[0] + max(d, .1)))
    assert ei.value.expected_epoch == 5 and ei.value.observed == -1
    publish_shard_map(kv, ShardMap([["a:1"]], epoch=3, job="j"))
    t[0] = 0.0
    with pytest.raises(ShardMapStale) as ei:
        wait_shard_map(kv, "j", min_epoch=5, timeout=2.0,
                       clock=lambda: t[0],
                       sleep=lambda d: t.__setitem__(0, t[0] + max(d, .1)))
    assert ei.value.observed == 3


# ---------------------------------------------------------------------------
# hardened wire protocol (satellites: barrier, unknown table, timeouts)
# ---------------------------------------------------------------------------

def test_barrier_timeout_typed_and_reset():
    srv = PSServer({0: _table()}, num_trainers=2,
                   barrier_timeout_s=0.2).start()
    c = PSClient([srv.endpoint])
    try:
        with pytest.raises(TimeoutError) as ei:
            c.barrier()          # only 1 of 2 trainers: must time out
        assert srv.endpoint in str(ei.value)
        # the barrier was RESET: a full 2-party round now succeeds
        # (v1 left it broken — every later barrier acked instantly
        # while synchronizing nothing)
        c2 = PSClient([srv.endpoint])
        errs = []

        def one(cl):
            try:
                cl.barrier()
            except BaseException as e:  # noqa: B036
                errs.append(e)

        ts = [threading.Thread(target=one, args=(cl,)) for cl in (c, c2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == []
        c2.close()
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_unknown_table_typed_connection_survives():
    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint])
    try:
        with pytest.raises(PSRequestError) as ei:
            c.pull(99, IDS, 4)
        assert "unknown table_id 99" in str(ei.value)
        with pytest.raises(PSRequestError):
            c.push(99, IDS, ONES, 4, lr=0.1)   # value payload drained too
        # same connection still serves — v1 killed the thread on the
        # KeyError and the client hung forever on the next reply
        c.push(0, IDS, ONES, 4, lr=0.25)
        np.testing.assert_allclose(c.pull(0, IDS, 4), -0.25)
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_dim_mismatch_typed():
    srv = PSServer({0: _table(4)}).start()
    c = PSClient([srv.endpoint])
    try:
        with pytest.raises(PSRequestError, match="dim mismatch"):
            c.push(0, IDS, np.ones((20, 8), np.float32), 8, lr=0.1)
        # pulls validate too: a wrong dim used to silently return
        # garbage (and desync the stream on the unread remainder)
        with pytest.raises(PSRequestError, match="dim mismatch"):
            c.pull(0, IDS, 8)
        np.testing.assert_allclose(c.pull(0, IDS, 4), 0.0)
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_plain_server_dedups_retried_write():
    """The hardened client replays a frame whose ack was lost — a plain
    (non-replicated) server must apply it exactly once too."""
    srv = PSServer({0: _table()}).start()
    try:
        ids = np.array([9], np.int64)
        vals = np.ones((1, 4), np.float32)
        frame = _HDR.pack(OP_PUSH, 0, 1, 0.5, 0, 77, 1, 4, 0, 0, 0) \
            + ids.tobytes() + vals.tobytes()
        peer = _RawPeer(srv.endpoint)
        peer.call_frame(frame)
        peer.call_frame(frame)           # the retry replay
        peer.close()
        np.testing.assert_allclose(srv.tables[0].pull(ids), -0.5)
    finally:
        srv.stop()


def test_concurrent_pushers_no_dedup_drop():
    """Write seqs are drawn under the shard lock, so two threads sharing
    one client can never have the earlier write swallowed by the
    server's high-watermark replay dedup."""
    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint])
    try:
        n_threads, per_thread = 4, 8

        def worker(t):
            for _ in range(per_thread):
                c.push(0, np.array([t], np.int64),
                       np.ones((1, 4), np.float32), 4, lr=0.125)

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        out = c.pull(0, np.arange(n_threads, dtype=np.int64), 4)
        np.testing.assert_allclose(out, -0.125 * per_thread)
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_malformed_header_error_frame_then_close():
    srv = PSServer({0: _table()}).start()
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        s.sendall(_HDR.pack(250, 0, 0, 0.0, 0, 0, 0, 0, 0, 0, 0))
        assert _recv_exact(s, 1) == b"\x00"
        code, _epoch, mlen = _ERR_HDR.unpack(_recv_exact(s, _ERR_HDR.size))
        assert code == ERR_BAD_REQUEST
        _recv_exact(s, mlen)
        assert s.recv(1) == b""      # unresyncable stream: closed
    finally:
        s.close()
        srv.stop()


def test_conn_idle_timeout_counter_and_transparent_reconnect():
    before = _counters()
    srv = PSServer({0: _table()}, request_timeout=0.15).start()
    c = PSClient([srv.endpoint])
    try:
        np.testing.assert_allclose(c.pull(0, IDS, 4), 0.0)
        time.sleep(0.5)              # server reaps the idle connection
        assert _delta(before, "ps_conn_timeouts") >= 1
        # the client's next call hits the dead socket, drops it, and
        # replays on a fresh connection — no error surfaces
        c.push(0, IDS, ONES, 4, lr=0.25)
        np.testing.assert_allclose(c.pull(0, IDS, 4), -0.25)
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


# ---------------------------------------------------------------------------
# bounded client RPCs: fault points, retries, typed exhaustion
# ---------------------------------------------------------------------------

def test_rpc_retry_via_fault_point_then_success():
    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint], sleep=lambda d: None)
    before = _counters()
    try:
        fault.arm("ps.pull", times=2, exc=ConnectionError)
        np.testing.assert_allclose(c.pull(0, IDS, 4), 0.0)
        assert _delta(before, "ps_rpc_retries") == 2
        assert _delta(before, "faults_injected") == 2
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_rpc_exhaustion_is_typed_psunavailable():
    port = _free_port()                      # nobody listening
    c = PSClient([f"127.0.0.1:{port}"], max_attempts=2,
                 connect_timeout=0.2, sleep=lambda d: None)
    before = _counters()
    with pytest.raises(PSUnavailable) as ei:
        c.pull(0, IDS, 4)
    assert ei.value.endpoint == f"127.0.0.1:{port}"
    assert ei.value.shard == 0
    assert _delta(before, "retry_giveups") == 1
    c.close()


def test_failed_rpc_drops_desynced_socket():
    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint], sleep=lambda d: None)
    try:
        np.testing.assert_allclose(c.pull(0, IDS, 4), 0.0)
        real = c._socks[0]

        class _FlakySock:
            """Delegating proxy that half-writes one header then dies —
            the mid-send failure that used to leave a desynced stream
            cached for the next call."""

            fired = False

            def sendall(self, data):
                if not self.fired:
                    self.fired = True
                    real.sendall(data[:3])   # half a header on the wire
                    raise OSError("injected mid-send failure")
                return real.sendall(data)

            def __getattr__(self, name):
                return getattr(real, name)

        proxy = _FlakySock()
        c._socks[0] = proxy
        # v1 kept the desynced socket cached and the next call read
        # garbage; now the failed attempt drops it and the retry replays
        # the WHOLE request on a fresh connection
        c.push(0, IDS, ONES, 4, lr=0.25)
        assert proxy.fired
        assert c._socks[0] is not proxy
        assert real.fileno() == -1           # old socket really closed
        np.testing.assert_allclose(c.pull(0, IDS, 4), -0.25)
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_heartbeat_loop_survives_outage_with_backoff():
    port = _free_port()
    srv = PSServer({0: _table()}, port=port,
                   heartbeat_timeout_s=30.0).start()
    c = PSClient([srv.endpoint], max_attempts=1, connect_timeout=0.2,
                 sleep=lambda d: None)
    try:
        c.start_heartbeat(trainer_id=0, interval_s=0.05)
        time.sleep(0.15)
        assert srv.monitor.alive(0)
        srv.crash()                      # pserver dies mid-job
        deadline = time.time() + 3
        while c.heartbeat_error is None and time.time() < deadline:
            time.sleep(0.02)
        assert c.heartbeat_error is not None
        assert c._hb_thread.is_alive()   # v1's loop silently returned
        # server comes back on the same endpoint: beats resume and the
        # parked error clears
        srv2 = PSServer({0: _table()}, port=port,
                        heartbeat_timeout_s=30.0).start()
        deadline = time.time() + 5
        while c.heartbeat_error is not None and time.time() < deadline:
            time.sleep(0.02)
        assert c.heartbeat_error is None
        assert srv2.monitor.alive(0)
        c.stop_heartbeat()
        srv2.stop()
    finally:
        c.close()
        srv.stop()


# ---------------------------------------------------------------------------
# replication: sync parity, dedup, divergence, async lag
# ---------------------------------------------------------------------------

def test_sync_replication_bitwise_parity(kv):
    coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        for _ in range(3):
            c.push(0, IDS, ONES, 4, lr=0.1)
        assert a.seq == b.seq == 3
        assert local_digest(a.tables[0]) == local_digest(b.tables[0])
        verify_replicas(fetch_shard_map(kv, "j"))
    finally:
        c.close()
        a.stop()
        b.stop()


def test_replica_diverged_typed(kv):
    coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        c.push(0, IDS, ONES, 4, lr=0.1)
        b.tables[0].assign(np.array([3], np.int64),
                           np.full((1, 4), 7.0, np.float32))
        with pytest.raises(ReplicaDiverged) as ei:
            verify_replicas(fetch_shard_map(kv, "j"))
        assert ei.value.shard == 0 and len(ei.value.digests) == 2
    finally:
        c.close()
        a.stop()
        b.stop()


def test_write_replay_dedups_exactly_once(kv):
    _coord, a, b = _mk_pair(kv)
    try:
        ids = np.array([7], np.int64)
        vals = np.ones((1, 4), np.float32)
        frame = _HDR.pack(OP_PUSH, 0, 1, 0.5, a.epoch, 42, 1, 4, 0, 0, 0) \
            + ids.tobytes() + vals.tobytes()
        peer = _RawPeer(a.endpoint)
        peer.call_frame(frame)
        peer.call_frame(frame)       # the failover replay: same (42, 1)
        peer.close()
        out = a.tables[0].pull(ids)
        np.testing.assert_allclose(out, -0.5)   # ONE sgd step, not two
        assert a.seq == 1
        np.testing.assert_allclose(b.tables[0].pull(ids), -0.5)
    finally:
        a.stop()
        b.stop()


def test_async_replication_bounded_lag_converges(kv):
    coord, a, b = _mk_pair(kv, sync=False)
    c = PSClient(kv=kv, job="j")
    try:
        for _ in range(5):
            c.push(0, IDS, ONES, 4, lr=0.1)
        a._replicator.flush(timeout=10.0)
        deadline = time.time() + 5
        while b.seq < a.seq and time.time() < deadline:
            time.sleep(0.02)
        assert b.seq == a.seq == 5
        assert local_digest(a.tables[0]) == local_digest(b.tables[0])
        assert "ps_replication_lag" in _counters()
    finally:
        c.close()
        a.stop()
        b.stop()


def test_gap_rejected_backup_self_heals(kv):
    """A live backup that missed forwards (marked down during a blip)
    must NOT apply out of order: the gap is rejected and a background
    delta catch-up reconverges it."""
    coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        c.push(0, IDS, ONES, 4, lr=0.1)
        b.crash()                      # blip: B misses two writes
        time.sleep(0.05)
        c.push(0, IDS, ONES, 4, lr=0.1)
        c.push(0, IDS, ONES, 4, lr=0.1)
        # B comes back on the same endpoint (fresh server object),
        # rejoins, catches up from A's delta log
        b2 = ReplicatedPSServer({0: _table()}, kv, job="j",
                                port=int(b.endpoint.rsplit(":", 1)[1]),
                                lease_ttl=10.0).start()
        assert b2.rejoin(timeout=5.0) == a.endpoint
        assert b2.seq == a.seq == 3
        assert local_digest(a.tables[0]) == local_digest(b2.tables[0])
        b2.stop()
    finally:
        c.close()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# failover: promotion, typed errors, replay
# ---------------------------------------------------------------------------

def test_promotion_failover_and_replay(kv):
    before = _counters()
    coord, a, b = _mk_pair(kv, lease_a=0.3, lease_b=10.0)
    c = PSClient(kv=kv, job="j", failover_timeout=10.0)
    try:
        c.push(0, IDS, ONES, 4, lr=0.5)
        a.crash()
        time.sleep(0.5)                  # A's 0.3s lease lapses; B's holds
        assert coord.check_now() == [0]
        m = fetch_shard_map(kv, "j")
        assert m.epoch == 2
        assert m.primary(0) == b.endpoint
        assert m.backups(0) == [a.endpoint]   # demoted to tail
        # the client's next write fails over and REPLAYS: nothing lost,
        # nothing doubled (2 pushes of lr .5 on grad 1 => -1.0)
        c.push(0, IDS, ONES, 4, lr=0.5)
        np.testing.assert_allclose(c.pull(0, IDS, 4), -1.0)
        assert c.epoch == 2
        assert b.role == "primary"
        assert _delta(before, "ps_failovers") >= 1
        assert _delta(before, "ps_promotions") == 1
        assert coord.promotions == 1
    finally:
        c.close()
        a.stop()
        b.stop()


def test_whole_group_dark_stays_typed_no_promotion(kv):
    before = _counters()
    coord, a, b = _mk_pair(kv, lease_a=0.2, lease_b=0.2)
    c = PSClient(kv=kv, job="j", failover_timeout=0.5,
                 max_attempts=1, connect_timeout=0.2,
                 sleep=lambda d: None)
    try:
        a.crash()
        b.crash()
        time.sleep(0.4)
        assert coord.check_now() == []       # nothing correct to promote
        with pytest.raises(PSUnavailable) as ei:
            c.push(0, IDS, ONES, 4, lr=0.1)
        assert ei.value.shard == 0
        assert _delta(before, "ps_promotions") == 0
    finally:
        c.close()
        a.stop()
        b.stop()


def test_demoted_primary_fences_inflight_write(kv):
    """A demoted primary that doesn't know it yet (inside its role_ttl
    window) must not silently lose an acked write: its sync forward is
    STALE-rejected by the newer-epoch peer, and the client's write is
    rejected typed for replay — never acked against stale state."""
    coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        c.push(0, IDS, ONES, 4, lr=0.1)
        # operator republish moves the job to epoch 2 with B primary;
        # B learns, A (old primary) does NOT (role_ttl pacing)
        coord.publish([[b.endpoint, a.endpoint]])
        b.refresh_role(force=True)
        assert b.role == "primary" and b.epoch == 2
        assert a.role == "primary" and a.epoch == 1   # stale, unaware
        # an epoch-1 client writing to A: A applies locally, forwards,
        # B STALE-rejects, A fences -> the client refreshes to the new
        # map and replays against B; dedup is per-server so nothing is
        # lost and nothing double-applied on the authoritative replica
        c.push(0, IDS, ONES, 4, lr=0.1)
        assert c.epoch == 2
        np.testing.assert_allclose(c.pull(0, IDS, 4), -0.2)
        assert a.role == "backup"        # the fence forced A's refresh
    finally:
        c.close()
        a.stop()
        b.stop()


def test_oversized_header_rejected_before_allocation():
    srv = PSServer({0: _table()}).start()
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        # n passes the id cap but n*dim would be a ~1 EiB allocation
        s.sendall(_HDR.pack(OP_PUSH, 0, 1 << 27, 0.0, 0, 0, 0,
                            0xFFFFF, 0, 0, 0))
        assert _recv_exact(s, 1) == b"\x00"
        code, _epoch, mlen = _ERR_HDR.unpack(_recv_exact(s, _ERR_HDR.size))
        assert code == ERR_BAD_REQUEST
    finally:
        s.close()
        srv.stop()


def test_embedding_communicator_mismatch_rejected():
    from paddle_tpu.ps import AsyncCommunicator, SparseEmbedding

    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint])
    try:
        comm = AsyncCommunicator(c, dim=4, table_id=0)
        with pytest.raises(ValueError, match="dim"):
            SparseEmbedding(8, client=c, communicator=comm)
        with pytest.raises(ValueError, match="table"):
            SparseEmbedding(4, client=c, table_id=1, communicator=comm)
        # communicator-only: pulls route through the communicator's
        # client, not a silently-fresh local table
        emb = SparseEmbedding(4, communicator=comm)
        assert emb._client is c
    finally:
        c.stop_servers()
        c.close()
        srv.stop()


def test_stale_epoch_client_auto_refreshes(kv):
    coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        c.push(0, IDS, ONES, 4, lr=0.1)
        assert c.epoch == 1
        # the coordinator republishes (an operator edit): same group,
        # epoch 2 — the server learns first, the client's next request
        # carries epoch 1, gets a typed STALE frame, refreshes, replays
        coord.publish([[a.endpoint, b.endpoint]])
        a.refresh_role(force=True)
        assert a.epoch == 2
        c.push(0, IDS, ONES, 4, lr=0.1)
        assert c.epoch == 2
        np.testing.assert_allclose(c.pull(0, IDS, 4), -0.2)
    finally:
        c.close()
        a.stop()
        b.stop()


def test_refresh_shard_map_bounded_typed(kv):
    _coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j", sleep=lambda d: None)
    try:
        with pytest.raises(ShardMapStale) as ei:
            c.refresh_shard_map(min_epoch=99, timeout=0.2)
        assert ei.value.expected_epoch == 99 and ei.value.observed == 1
    finally:
        c.close()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# crash-safe shard snapshots + recovery (SnapshotStore + corrupt_ckpt)
# ---------------------------------------------------------------------------

def test_snapshot_restore_catchup(kv, tmp_path):
    before = _counters()
    coord, a, b = _mk_pair(kv, lease_a=0.3, lease_b=10.0,
                           snap_a=str(tmp_path / "A"), snapshot_every=2)
    c = PSClient(kv=kv, job="j", failover_timeout=10.0)
    try:
        for _ in range(5):               # snapshots at seq 2 and 4
            c.push(0, IDS, ONES, 4, lr=0.1)
        assert _delta(before, "ps_snapshot_commits") == 2
        assert sorted(os.listdir(tmp_path / "A" / "shard_0")) == \
            ["seq_2", "seq_4"]
        a.crash()
        time.sleep(0.5)
        assert coord.check_now() == [0]
        c.push(0, IDS, ONES, 4, lr=0.1)  # write 6 lands on promoted B
        # relaunch A on its endpoint: restore seq_4, replay 5..6 from B
        a2 = ReplicatedPSServer({0: _table()}, kv, job="j",
                                port=int(a.endpoint.rsplit(":", 1)[1]),
                                lease_ttl=10.0,
                                snapshot_dir=str(tmp_path / "A"))
        a2.start()
        assert a2.rejoin(timeout=5.0) == b.endpoint
        assert a2.seq == b.seq == 6
        assert a2.role == "backup"
        assert local_digest(a2.tables[0]) == local_digest(b.tables[0])
        a2.stop()
    finally:
        c.close()
        a.stop()
        b.stop()


def test_corrupt_snapshot_falls_back_then_heals(kv, tmp_path):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import corrupt_ckpt

    before = _counters()
    coord, a, b = _mk_pair(kv, snap_a=str(tmp_path / "A"),
                           snapshot_every=2)
    c = PSClient(kv=kv, job="j")
    try:
        for _ in range(4):               # snapshots at seq 2 and 4
            c.push(0, IDS, ONES, 4, lr=0.1)
        a.crash()
        # damage the NEWEST shard snapshot through the chaos tool (it
        # must find the shard_<k>/seq_<n> layout on its own)
        report = corrupt_ckpt.corrupt(str(tmp_path / "A"), mode="flip")
        assert report["snapshot"].endswith("seq_4")
        a2 = ReplicatedPSServer({0: _table()}, kv, job="j",
                                port=int(a.endpoint.rsplit(":", 1)[1]),
                                lease_ttl=10.0,
                                snapshot_dir=str(tmp_path / "A"))
        a2.start()
        # restore skips the corrupt seq_4 (sha mismatch), falls back to
        # seq_2, and the delta catch-up heals the rest
        a2.rejoin(timeout=5.0)
        assert _delta(before, "ckpt_corrupt_skipped") >= 1
        assert _delta(before, "ckpt_fallbacks") >= 1
        assert a2.seq == b.seq == 4
        assert local_digest(a2.tables[0]) == local_digest(b.tables[0])
        a2.stop()
    finally:
        c.close()
        a.stop()
        b.stop()


def test_delta_log_truncation_forces_full_state_transfer(kv):
    _coord, a, b = _mk_pair(kv)
    c = PSClient(kv=kv, job="j")
    try:
        a._dlog = DeltaLog(capacity=2)   # tiny log: rotates fast
        for _ in range(5):
            c.push(0, IDS, ONES, 4, lr=0.1)
        fresh = ReplicatedPSServer({0: _table()}, kv, job="j",
                                   port=_free_port(), lease_ttl=10.0)
        # no start needed: catch_up is a pure client of A
        assert fresh._dlog.since(0) == []
        n = fresh.catch_up(a.endpoint)
        assert n == 1                    # one table, full transfer
        assert fresh.seq == a.seq == 5
        assert local_digest(fresh.tables[0]) == local_digest(a.tables[0])
        # dedup state rides the transfer: a replay of write 5 is a no-op
        assert fresh._applied == a._applied
        fresh.stop()
        # an EMPTY log on a server that is ahead (snapshot-restored, no
        # deltas retained) must also force the full transfer — "0
        # entries" would leave the rejoiner silently diverged at seq 0
        a._dlog = DeltaLog(capacity=8)
        fresh2 = ReplicatedPSServer({0: _table()}, kv, job="j",
                                    port=_free_port(), lease_ttl=10.0)
        assert fresh2.catch_up(a.endpoint) == 1   # full transfer again
        assert fresh2.seq == a.seq == 5
        assert local_digest(fresh2.tables[0]) == local_digest(a.tables[0])
        fresh2.stop()
    finally:
        c.close()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# embedding + communicator on the typed error path
# ---------------------------------------------------------------------------

def test_sparse_embedding_remote_roundtrip_and_failover(kv):
    import paddle_tpu as paddle
    from paddle_tpu.ps import SparseEmbedding

    coord, a, b = _mk_pair(kv, lease_a=0.3, lease_b=10.0)
    c = PSClient(kv=kv, job="j", failover_timeout=10.0)
    try:
        emb = SparseEmbedding(4, client=c)
        ids = paddle.to_tensor(np.array([1, 2, 3], np.int64))
        out = emb(ids)
        assert tuple(out.shape) == (3, 4)
        loss = (out * out).sum()
        loss.backward()
        emb.push_gradients(lr=0.5)
        ref_after_one = c.pull(0, np.array([1, 2, 3], np.int64), 4)
        # primary dies; the next pull/push cycle rides the failover
        a.crash()
        time.sleep(0.5)
        assert coord.check_now() == [0]
        out = emb(ids)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref_after_one)
        loss = (out * out).sum()
        loss.backward()
        emb.push_gradients(lr=0.5)       # lands on the promoted backup
        assert c.epoch == 2
    finally:
        c.close()
        a.stop()
        b.stop()


def test_sparse_embedding_through_communicator():
    import paddle_tpu as paddle
    from paddle_tpu.ps import AsyncCommunicator, SparseEmbedding

    srv = PSServer({0: _table()}).start()
    c = PSClient([srv.endpoint])
    comm = AsyncCommunicator(c, dim=4, lr=0.5).start()
    try:
        emb = SparseEmbedding(4, client=c, communicator=comm)
        out = emb(paddle.to_tensor(np.array([5, 6], np.int64)))
        (out.sum()).backward()
        emb.push_gradients(lr=0.5)
        comm.flush()
        got = c.pull(0, np.array([5, 6], np.int64), 4)
        np.testing.assert_allclose(got, -0.5)   # grad of sum() is ones
    finally:
        comm.stop()
        c.stop_servers()
        c.close()
        srv.stop()


def test_communicator_flush_surfaces_psunavailable():
    from paddle_tpu.ps import AsyncCommunicator

    port = _free_port()                  # dead pserver
    c = PSClient([f"127.0.0.1:{port}"], max_attempts=1,
                 connect_timeout=0.2, sleep=lambda d: None)
    comm = AsyncCommunicator(c, dim=4)
    comm.start()
    comm.push_sparse_grad(IDS, ONES)
    # the send thread's push exits typed (PSUnavailable after the
    # bounded retries) and parks; flush must surface THAT — the pserver
    # died, not the sender — instead of mislabeling it WorkerLost
    with pytest.raises(PSUnavailable) as ei:
        comm.flush(timeout=10.0)
    assert ei.value.endpoint == f"127.0.0.1:{port}"
    c.close()


# ---------------------------------------------------------------------------
# counters surface
# ---------------------------------------------------------------------------

def test_ps_counters_merge_into_exe_counters():
    import paddle_tpu.static as static

    assert set(profiler.PS_COUNTER_NAMES) == {
        "ps_failovers", "ps_promotions", "ps_rpc_retries",
        "ps_snapshot_commits", "ps_replication_lag", "ps_conn_timeouts"}
    profiler.bump_counter("ps_failovers", 0)
    profiler.bump_counter("ps_promotions", 0)
    exe = static.Executor()
    counters = exe.counters
    assert "ps_failovers" in counters
    assert "ps_promotions" in counters


# ---------------------------------------------------------------------------
# the crown: deterministic kill-a-primary chaos drill (subprocess)
# ---------------------------------------------------------------------------

def test_ps_chaos_drill_kill_primary(tmp_path, monkeypatch):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import chaos_drill

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PYTHONPATH", _REPO)
    monkeypatch.delenv("PADDLE_FAULT_SPEC", raising=False)
    # lease_ttl 3.0 = the elastic drill's proven CI value: shorter TTLs
    # can expire spuriously on the loaded 2-core box (GIL-starved KV
    # renewal), promoting the backup before the kill lands and routing
    # the drill down the fence path instead of the crash-failover path
    report = chaos_drill.run_ps_drill(str(tmp_path), pushes=12,
                                      kill_after=5, snapshot_every=3,
                                      lease_ttl=3.0)
    assert report.get("error") is None, report
    # zero lost updates, zero doubles: the final pull is BITWISE equal
    # to the never-killed reference stream
    assert report["parity_bitwise"], report
    # the backup was promoted via a shard-map epoch bump and the client
    # failed over with typed errors only (a hang would time the drill out)
    assert report["epoch"] == 2, report
    assert report["counters"]["ps_promotions"] == 1, report
    assert report["counters"]["ps_failovers"] >= 1, report
    assert report["counters"]["ps_snapshot_commits"] >= 1, report
    # the killed primary was relaunched once, restored its snapshot,
    # caught up from the promoted backup's delta log, and reconverged
    assert report["supervisor"]["restarts_by_rank"] == {0: 1}, report
    assert report["replicas_converged"] and report["digest_parity"], report
    assert report["ok"], report
