"""QAT fake-quant training + PTQ calibration + int8 conversion."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (
    PTQ, QAT, QuantConfig, QuantedConv2D, QuantedLinear, export_int8, fake_quant,
)


def test_fake_quant_grid_and_ste():
    import jax
    import jax.numpy as jnp

    x = jnp.linspace(-1.0, 1.0, 11)
    out = np.asarray(fake_quant(x, jnp.asarray(1.0), 8).numpy())
    # values land on the int8 grid scale/127
    grid = np.round(out * 127)
    np.testing.assert_allclose(out, grid / 127, atol=1e-6)

    # STE: gradient of sum(fake_quant(x)) wrt x is 1 everywhere in range
    xt = paddle.to_tensor(np.array([0.3, -0.7], np.float32))
    xt.stop_gradient = False
    y = fake_quant(xt, jnp.asarray(1.0), 8)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(xt.grad.numpy()), 1.0)


def _lenet_ish():
    paddle.seed(0)
    return nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
        nn.Flatten(), nn.Linear(4 * 8 * 8, 10),
    )


def test_qat_wraps_and_trains():
    model = _lenet_ish()
    model = QAT(QuantConfig()).quantize(model)
    kinds = [type(s).__name__ for _, s in model.named_sublayers()]
    assert "QuantedConv2D" in kinds and "QuantedLinear" in kinds

    opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)).astype(np.int64))
    losses = []
    for _ in range(8):
        logits = model(x)
        loss = nn.functional.cross_entropy(logits, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_ptq_calibration_then_convert_close_to_fp():
    model = _lenet_ish()
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(16, 1, 8, 8).astype(np.float32))
    model.eval()
    ref = model(x).numpy()

    ptq = PTQ()
    qmodel = ptq.quantize(model)
    for _ in range(4):            # calibration passes
        qmodel(x)
    qmodel = ptq.convert(qmodel)
    out = qmodel(x).numpy()
    # int8 simulation stays close to fp32 output
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err

    table = export_int8(qmodel)
    assert len(table) == 2
    for rec in table.values():
        assert rec["weight_int8"].dtype == np.int8
        assert rec["weight_scale"] > 0
        assert rec["act_scale"] > 0
