"""QAT fake-quant training + PTQ calibration + int8 conversion +
inference round trip (reference slim quantization_pass.py +
test_quantization_pass.py freeze/save coverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (
    PTQ, QAT, QuantConfig, QuantedConv2D, QuantedLinear,
    convert_to_inference, export_int8, fake_quant, save_quantized,
)

pytestmark = pytest.mark.slow


def test_fake_quant_grid_and_ste():
    import jax
    import jax.numpy as jnp

    x = jnp.linspace(-1.0, 1.0, 11)
    out = np.asarray(fake_quant(x, jnp.asarray(1.0), 8).numpy())
    # values land on the int8 grid scale/127
    grid = np.round(out * 127)
    np.testing.assert_allclose(out, grid / 127, atol=1e-6)

    # STE: gradient of sum(fake_quant(x)) wrt x is 1 everywhere in range
    xt = paddle.to_tensor(np.array([0.3, -0.7], np.float32))
    xt.stop_gradient = False
    y = fake_quant(xt, jnp.asarray(1.0), 8)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(xt.grad.numpy()), 1.0)


def _lenet_ish():
    paddle.seed(0)
    return nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
        nn.Flatten(), nn.Linear(4 * 8 * 8, 10),
    )


def test_qat_wraps_and_trains():
    model = _lenet_ish()
    model = QAT(QuantConfig()).quantize(model)
    kinds = [type(s).__name__ for _, s in model.named_sublayers()]
    assert "QuantedConv2D" in kinds and "QuantedLinear" in kinds

    opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)).astype(np.int64))
    losses = []
    for _ in range(8):
        logits = model(x)
        loss = nn.functional.cross_entropy(logits, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_ptq_calibration_then_convert_close_to_fp():
    model = _lenet_ish()
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(16, 1, 8, 8).astype(np.float32))
    model.eval()
    ref = model(x).numpy()

    ptq = PTQ()
    qmodel = ptq.quantize(model)
    for _ in range(4):            # calibration passes
        qmodel(x)
    qmodel = ptq.convert(qmodel)
    out = qmodel(x).numpy()
    # int8 simulation stays close to fp32 output
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err

    table = export_int8(qmodel)
    assert len(table) == 2
    for rec in table.values():
        assert rec["weight_int8"].dtype == np.int8
        assert rec["weight_scale"] > 0
        assert rec["act_scale"] > 0


def test_channel_wise_scales_beat_per_tensor():
    """Per-out-channel scales must quantize a weight whose channels have
    wildly different magnitudes with far less error than one global scale
    (reference quantization_pass.py channel_wise_abs_max motivation)."""
    rng = np.random.RandomState(0)
    w = rng.randn(16, 8).astype(np.float32)
    w[:, 0] *= 100.0  # one huge channel wrecks a per-tensor scale
    lin = nn.Linear(16, 8)
    lin.weight.set_value(w)

    def quant_err(qtype):
        q = QAT(QuantConfig(weight_quantize_type=qtype)).quantize(
            nn.Sequential(lin))
        layer = q[0]
        wq = layer._q_weight(layer.inner.weight).numpy()
        small = w[:, 1:]
        return np.abs(wq[:, 1:] - small).max() / np.abs(small).max()

    per_tensor = quant_err("abs_max")
    per_channel = quant_err("channel_wise_abs_max")
    assert per_channel < per_tensor / 10, (per_tensor, per_channel)

    table = export_int8(QAT(QuantConfig(
        weight_quantize_type="channel_wise_abs_max")).quantize(
            nn.Sequential(nn.Linear(4, 6))))
    (rec,) = table.values()
    assert rec["weight_scale"].shape == (6,)
    assert rec["quant_type"] == "channel_wise_abs_max"


def test_quantized_inference_round_trip(tmp_path):
    """train -> quantize -> save -> create_predictor -> run parity
    (VERDICT r1 item 6; reference freeze-pass + AnalysisPredictor loop)."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static import InputSpec

    model = _lenet_ish()
    qmodel = QAT(QuantConfig(
        weight_quantize_type="channel_wise_abs_max")).quantize(model)
    opt = optimizer.Adam(learning_rate=1e-2,
                         parameters=qmodel.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)).astype(np.int64))
    for _ in range(4):
        loss = nn.functional.cross_entropy(qmodel(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

    qmodel.eval()
    ref = qmodel(x).numpy()

    prefix = str(tmp_path / "quant_lenet")
    save_quantized(qmodel, prefix,
                   input_spec=[InputSpec([8, 1, 8, 8], "float32")])

    pred = create_predictor(Config(prefix + ".pdmodel"))
    (out,) = pred.run([x.numpy()])
    # int8 inference layers reproduce the fake-quant eval forward closely
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.05, err
    assert np.array_equal(np.argmax(out, -1), np.argmax(ref, -1))


# ---------------------------------------------------------------------------
# round 3 (VERDICT r2 item 9): observers, embedding/matmul int8, dataset PTQ
# ---------------------------------------------------------------------------


def test_observers_match_numpy_references():
    import numpy as np

    from paddle_tpu.quantization import (AbsMaxObserver, MSEObserver,
                                         MovingAverageAbsMaxObserver,
                                         PercentileObserver)

    rng = np.random.RandomState(0)
    batches = [rng.randn(1000).astype(np.float32) * (i + 1)
               for i in range(4)]

    ob = AbsMaxObserver()
    for b in batches:
        ob.observe(b)
    assert np.isclose(ob.scale(),
                      max(float(np.abs(b).max()) for b in batches))

    ob = MovingAverageAbsMaxObserver(0.9)
    ref = None
    for b in batches:
        amax = float(np.abs(b).max())
        ref = amax if ref is None else 0.9 * ref + 0.1 * amax
    for b in batches:
        ob.observe(b)
    assert np.isclose(ob.scale(), ref)

    ob = PercentileObserver(percentile=99.0, bins=4096)
    allv = np.abs(np.concatenate(batches))
    for b in batches:
        ob.observe(b)
    ref = float(np.percentile(allv, 99.0))
    assert abs(ob.scale() - ref) / ref < 0.02   # bin-width tolerance

    ob = MSEObserver(bit_length=8, bins=4096)
    for b in batches:
        ob.observe(b)
    s = ob.scale()
    # MSE-optimal scale for a heavy-tailed mix clips some outliers:
    # strictly below absmax, above the median
    assert 0 < s <= float(allv.max())
    assert s > float(np.median(allv))


def test_int8_matmul_matches_fake_quant_path():
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.quantization import int8_matmul

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 16), jnp.float32)
    w = rng.randn(16, 8).astype(np.float32)
    w_scale = np.abs(w).max()
    w_q = np.clip(np.round(w / w_scale * 127), -128, 127).astype(np.int8)
    w_mult = np.float32(w_scale / 127)
    x_scale = jnp.asarray(float(jnp.abs(x).max()), jnp.float32)

    got = int8_matmul(x, jnp.asarray(w_q), x_scale, w_mult)
    # reference: dequantize both and matmul in f64 (exact for int8 mags)
    x_q = np.clip(np.round(np.asarray(x) / float(x_scale) * 127),
                  -128, 127)
    ref = (x_q * float(x_scale) / 127) @ (w_q.astype(np.float64) * w_mult)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5)


def test_embedding_int8_roundtrip():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import (PTQ, QuantConfig,
                                         convert_to_inference)

    paddle.seed(0)
    emb = nn.Embedding(50, 16, padding_idx=0)
    ids = paddle.to_tensor(np.array([[1, 2, 0, 7]], np.int64))
    ref = emb(ids).numpy()

    ptq = PTQ(QuantConfig(algo="abs_max"))
    m = ptq.quantize(emb)
    m(ids)
    m = ptq.convert(m)
    m = convert_to_inference(m)
    from paddle_tpu.quantization import Int8Embedding

    assert isinstance(m, Int8Embedding) or any(
        isinstance(s, Int8Embedding) for _, s in m.named_sublayers())
    got = m(ids).numpy()
    # int8 table: rows match within quantization step of the table scale
    step = float(np.abs(emb.weight.numpy()).max()) / 127
    assert np.abs(got - ref).max() <= step
    # padding row stays exactly zero
    np.testing.assert_allclose(got[0, 2], 0.0, atol=0)


def test_ptq_bert_encoder_accuracy_delta():
    """PTQ of the bench BERT encoder (VERDICT r2 item 9 'Done' bar):
    calibrate on sample batches with the percentile observer, convert
    to int8 inference layers, and assert the masked-LM loss moves by
    <2% relative to fp32."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.quantization import (QuantConfig,
                                         convert_to_inference,
                                         post_training_quantization)

    paddle.seed(0)
    cfg = BertConfig.tiny()
    cfg.num_hidden_layers = 2
    model = BertForPretraining(cfg)
    model.eval()

    rng = np.random.RandomState(0)

    def batch(seed):
        r = np.random.RandomState(seed)
        ids = paddle.to_tensor(
            r.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        tt = paddle.to_tensor(np.zeros((2, 16), np.int32))
        mlm = paddle.to_tensor(
            r.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        nsp = paddle.to_tensor(r.randint(0, 2, (2,)).astype(np.int32))
        return ids, tt, mlm, nsp

    eval_b = batch(99)
    fp32_loss = float(model.loss(*eval_b).numpy())

    qmodel = post_training_quantization(
        model, [batch(i)[:2] for i in range(6)],
        QuantConfig(algo="percentile", percentile=99.99,
                    weight_quantize_type="channel_wise_abs_max"),
        forward=lambda m, b: m(*b))
    qmodel = convert_to_inference(qmodel)
    int8_loss = float(qmodel.loss(*eval_b).numpy())
    delta = abs(int8_loss - fp32_loss) / max(abs(fp32_loss), 1e-6)
    assert np.isfinite(int8_loss)
    assert delta < 0.02, (fp32_loss, int8_loss, delta)


def test_bare_root_linear_ptq_roundtrip():
    """A quantizable layer AS the model root must calibrate, convert,
    and export like a nested one (review regression: the root was
    skipped by named_sublayers, leaving act_scale at 0)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import (PTQ, QuantConfig,
                                         convert_to_inference,
                                         export_int8)

    paddle.seed(0)
    lin = nn.Linear(8, 4)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    ref = lin(x).numpy()
    ptq = PTQ(QuantConfig(algo="abs_max"))
    m = ptq.quantize(lin)
    m(x)
    m = ptq.convert(m)
    art = export_int8(m)
    assert "" in art and art[""]["act_scale"] > 0
    m = convert_to_inference(m)
    got = m(x).numpy()
    assert np.abs(got - ref).max() < 0.2


def test_int8_matmul_overflow_guard_falls_back():
    """K large enough to overflow the int32 accumulator routes to the
    f32 dequantized matmul (sign-correct), not silent wraparound."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.quantization import int8_matmul

    k = (1 << 17) + 128          # beyond the 131071 exactness bound
    x = jnp.ones((1, k), jnp.float32)
    w_q = np.full((k, 2), 127, np.int8)
    got = int8_matmul(x, jnp.asarray(w_q), jnp.asarray(1.0), 1.0 / 127)
    ref = float(k)               # all-ones x at full scale, w = 1.0
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-3)
    assert (np.asarray(got) > 0).all()   # wraparound would flip sign
