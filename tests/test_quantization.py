"""QAT fake-quant training + PTQ calibration + int8 conversion +
inference round trip (reference slim quantization_pass.py +
test_quantization_pass.py freeze/save coverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (
    PTQ, QAT, QuantConfig, QuantedConv2D, QuantedLinear,
    convert_to_inference, export_int8, fake_quant, save_quantized,
)

pytestmark = pytest.mark.slow


def test_fake_quant_grid_and_ste():
    import jax
    import jax.numpy as jnp

    x = jnp.linspace(-1.0, 1.0, 11)
    out = np.asarray(fake_quant(x, jnp.asarray(1.0), 8).numpy())
    # values land on the int8 grid scale/127
    grid = np.round(out * 127)
    np.testing.assert_allclose(out, grid / 127, atol=1e-6)

    # STE: gradient of sum(fake_quant(x)) wrt x is 1 everywhere in range
    xt = paddle.to_tensor(np.array([0.3, -0.7], np.float32))
    xt.stop_gradient = False
    y = fake_quant(xt, jnp.asarray(1.0), 8)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(xt.grad.numpy()), 1.0)


def _lenet_ish():
    paddle.seed(0)
    return nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
        nn.Flatten(), nn.Linear(4 * 8 * 8, 10),
    )


def test_qat_wraps_and_trains():
    model = _lenet_ish()
    model = QAT(QuantConfig()).quantize(model)
    kinds = [type(s).__name__ for _, s in model.named_sublayers()]
    assert "QuantedConv2D" in kinds and "QuantedLinear" in kinds

    opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)).astype(np.int64))
    losses = []
    for _ in range(8):
        logits = model(x)
        loss = nn.functional.cross_entropy(logits, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_ptq_calibration_then_convert_close_to_fp():
    model = _lenet_ish()
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(16, 1, 8, 8).astype(np.float32))
    model.eval()
    ref = model(x).numpy()

    ptq = PTQ()
    qmodel = ptq.quantize(model)
    for _ in range(4):            # calibration passes
        qmodel(x)
    qmodel = ptq.convert(qmodel)
    out = qmodel(x).numpy()
    # int8 simulation stays close to fp32 output
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err

    table = export_int8(qmodel)
    assert len(table) == 2
    for rec in table.values():
        assert rec["weight_int8"].dtype == np.int8
        assert rec["weight_scale"] > 0
        assert rec["act_scale"] > 0


def test_channel_wise_scales_beat_per_tensor():
    """Per-out-channel scales must quantize a weight whose channels have
    wildly different magnitudes with far less error than one global scale
    (reference quantization_pass.py channel_wise_abs_max motivation)."""
    rng = np.random.RandomState(0)
    w = rng.randn(16, 8).astype(np.float32)
    w[:, 0] *= 100.0  # one huge channel wrecks a per-tensor scale
    lin = nn.Linear(16, 8)
    lin.weight.set_value(w)

    def quant_err(qtype):
        q = QAT(QuantConfig(weight_quantize_type=qtype)).quantize(
            nn.Sequential(lin))
        layer = q[0]
        wq = layer._q_weight(layer.inner.weight).numpy()
        small = w[:, 1:]
        return np.abs(wq[:, 1:] - small).max() / np.abs(small).max()

    per_tensor = quant_err("abs_max")
    per_channel = quant_err("channel_wise_abs_max")
    assert per_channel < per_tensor / 10, (per_tensor, per_channel)

    table = export_int8(QAT(QuantConfig(
        weight_quantize_type="channel_wise_abs_max")).quantize(
            nn.Sequential(nn.Linear(4, 6))))
    (rec,) = table.values()
    assert rec["weight_scale"].shape == (6,)
    assert rec["quant_type"] == "channel_wise_abs_max"


def test_quantized_inference_round_trip(tmp_path):
    """train -> quantize -> save -> create_predictor -> run parity
    (VERDICT r1 item 6; reference freeze-pass + AnalysisPredictor loop)."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static import InputSpec

    model = _lenet_ish()
    qmodel = QAT(QuantConfig(
        weight_quantize_type="channel_wise_abs_max")).quantize(model)
    opt = optimizer.Adam(learning_rate=1e-2,
                         parameters=qmodel.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 1, 8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)).astype(np.int64))
    for _ in range(4):
        loss = nn.functional.cross_entropy(qmodel(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

    qmodel.eval()
    ref = qmodel(x).numpy()

    prefix = str(tmp_path / "quant_lenet")
    save_quantized(qmodel, prefix,
                   input_spec=[InputSpec([8, 1, 8, 8], "float32")])

    pred = create_predictor(Config(prefix + ".pdmodel"))
    (out,) = pred.run([x.numpy()])
    # int8 inference layers reproduce the fake-quant eval forward closely
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.05, err
    assert np.array_equal(np.argmax(out, -1), np.argmax(ref, -1))
