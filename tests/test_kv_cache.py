"""PageTableManager prefix sharing + refcounts (inference/decode/
kv_cache.py): the chained-hash prefix index, shared-page refcounts,
the cached-page LRU, copy-on-write, and eviction under sharing — the
invariants the engine leans on: a shared page is NEVER reclaimed from
under another holder, a refcount never goes negative, and a repeated
prefix allocates zero new pages."""
import numpy as np
import pytest

from paddle_tpu.inference.decode.kv_cache import (PageTableManager,
                                                  _chain_keys)


def _pool(n_pages=16, page_size=4, max_pages_per_seq=6):
    return PageTableManager(n_pages=n_pages, page_size=page_size,
                            max_pages_per_seq=max_pages_per_seq)


TOKS = list(range(1, 13))                      # 12 tokens = 3 full pages


def _share_scene():
    """seq 1 owns a 3-page registered prefix; seq 2 shares all 3 pages
    plus one fresh suffix page."""
    pool = _pool()
    p1 = pool.alloc_seq(1, len(TOKS))
    pool.register_prefix(1, TOKS)
    shared = pool.match_prefix(TOKS + [99, 100], limit=3)
    p2 = pool.alloc_seq_shared(2, shared, len(TOKS) + 2)
    return pool, p1, p2


# ---------------------------------------------------------------------------
# prefix index
# ---------------------------------------------------------------------------
def test_chain_keys_fold_the_whole_prefix():
    """key_i must cover tokens [0, (i+1)*S): identical page CONTENT
    after a different prefix hashes differently."""
    a = _chain_keys([1, 2, 3, 4, 5, 6, 7, 8], 2, 4)
    b = _chain_keys([9, 9, 9, 9, 5, 6, 7, 8], 2, 4)
    assert a[0] != b[0] and a[1] != b[1]
    assert a == _chain_keys([1, 2, 3, 4, 5, 6, 7, 8, 99], 2, 4)


def test_match_prefix_chain_and_limit():
    pool = _pool()
    pages = pool.alloc_seq(1, len(TOKS))
    pool.register_prefix(1, TOKS)
    assert pool.match_prefix(TOKS + [77]) == pages
    assert pool.match_prefix(TOKS[:8] + [77, 78]) == pages[:2]
    # chain breaks at the first divergent page — later matches can't
    # resurrect it
    divergent = [42] * 4 + TOKS[4:]
    assert pool.match_prefix(divergent) == []
    # the prefill caller's cap: at least one suffix token must remain
    assert pool.match_prefix(TOKS + [77], limit=2) == pages[:2]
    assert pool.match_prefix(TOKS[:3]) == []       # no full page


def test_register_prefix_idempotent_and_partial():
    pool = _pool()
    pool.alloc_seq(1, 10)                          # 3 pages, 2 full
    assert pool.register_prefix(1, TOKS[:10]) == 2
    assert pool.register_prefix(1, TOKS[:10]) == 0  # already indexed
    # a second sequence with the same prefix doesn't double-index
    pool.alloc_seq(2, 10)
    assert pool.register_prefix(2, TOKS[:10]) == 0


# ---------------------------------------------------------------------------
# shared refcounts
# ---------------------------------------------------------------------------
def test_shared_alloc_refcounts_and_hit_accounting():
    pool, p1, p2 = _share_scene()
    assert p2[:3] == p1 and len(p2) == 4
    for p in p1:
        assert pool.page_ref(p) == 2
    assert pool.pages_shared == 3
    assert pool.prefix_hits == 3
    # shared pages count ONCE toward occupancy
    assert pool.pages_in_use == 4


def test_repeated_prefix_allocates_zero_new_pages():
    """The acceptance gate: a full-prefix hit consumes no fresh pages
    for the shared span — only the suffix allocates."""
    pool, p1, p2 = _share_scene()
    free_before = pool.pages_free
    shared = pool.match_prefix(TOKS + [7], limit=3)
    p3 = pool.alloc_seq_shared(3, shared, len(TOKS) + 1)
    assert p3[:3] == p1
    # exactly ONE fresh page (the suffix), zero for the prefix
    assert pool.pages_free == free_before - 1
    assert all(pool.page_ref(p) == 3 for p in p1)


def test_free_of_shared_page_decrements_not_frees():
    pool, p1, p2 = _share_scene()
    free_before = pool.pages_free
    assert pool.free_seq(1) == 3
    # seq 2 still holds every shared page: nothing returned to the pool
    assert pool.pages_free == free_before
    assert all(pool.page_ref(p) == 1 for p in p1)
    assert pool.pages_shared == 0
    # last holder drops: indexed pages park in the cached LRU (KV still
    # valid for future hits), the unindexed suffix page goes free
    pool.free_seq(2)
    assert pool.pages_cached == 3
    assert pool.pages_in_use == 0
    assert pool.match_prefix(TOKS + [5]) == p1     # still matchable


def test_evict_while_shared_never_reclaims_from_holder():
    pool, p1, p2 = _share_scene()
    assert pool.evict_seq(1) == 3
    assert pool.evicted_pages == 3
    # the survivor's table is intact and its pages never re-enter the
    # allocator while it holds them
    assert pool.seq_pages(2) == p2
    assert all(pool.page_ref(p) == 1 for p in p2)
    grabbed = []
    while True:
        got = pool.alloc_seq(100 + len(grabbed), 4 * 6)
        if got is None:
            break
        grabbed.extend(got)
    assert not (set(grabbed) & set(p2)), \
        "allocator handed out a page a live sequence still holds"


def test_refcount_never_goes_negative():
    pool = _pool()
    (page,) = pool.alloc_seq(1, 4)
    pool.free_seq(1)
    with pytest.raises(ValueError, match="below refcount 0"):
        pool._release_page(page)
    # double-free via the public API is a no-op (table row is gone)
    assert pool.free_seq(1) == 0


def test_peak_tracking_survives_frees():
    pool, p1, p2 = _share_scene()
    assert pool.peak_pages_in_use == 4
    assert pool.peak_pages_shared == 3
    pool.free_seq(1)
    pool.free_seq(2)
    assert pool.pages_in_use == 0
    assert pool.peak_pages_in_use == 4
    assert pool.peak_pages_shared == 3


# ---------------------------------------------------------------------------
# cached LRU: revival and reclaim
# ---------------------------------------------------------------------------
def test_cached_pages_revive_without_allocation():
    pool = _pool()
    p1 = pool.alloc_seq(1, len(TOKS))
    pool.register_prefix(1, TOKS)
    pool.free_seq(1)
    assert pool.pages_cached == 3 and pool.pages_in_use == 0
    shared = pool.match_prefix(TOKS + [7], limit=3)
    assert shared == p1
    p2 = pool.alloc_seq_shared(2, shared, len(TOKS) + 1)
    assert p2[:3] == p1
    assert pool.pages_cached == 0                  # revived, not copied
    assert pool.prefix_hits == 3


def test_cached_lru_reclaim_drops_index_entry():
    pool = _pool(n_pages=6, page_size=4, max_pages_per_seq=5)
    toks = TOKS[:8]
    p1 = pool.alloc_seq(1, 8)
    pool.register_prefix(1, toks)
    pool.free_seq(1)
    assert pool.pages_cached == 2
    # demand exceeds the free list: the LRU-oldest cached page is
    # reclaimed and its index entry dies with it
    p2 = pool.alloc_seq(2, 4 * 5)
    assert p2 is not None and len(p2) == 5
    snap = pool.snapshot()
    assert snap["cached_reclaimed"] == 2
    assert pool.match_prefix(toks + [1]) == []
    assert set(p1) <= set(p2)                      # pages were reused


# ---------------------------------------------------------------------------
# copy-on-write
# ---------------------------------------------------------------------------
def test_cow_exclusive_indexed_page_unindexes_in_place():
    pool = _pool()
    p1 = pool.alloc_seq(1, len(TOKS))
    pool.register_prefix(1, TOKS)
    assert pool.needs_cow(1, 2)                    # indexed, though ref 1
    assert pool.cow_page(1, 2) is None             # sole owner: mutate
    assert not pool.needs_cow(1, 2)
    assert pool.match_prefix(TOKS + [7]) == []     # index entry dropped
    assert pool.seq_pages(1) == p1                 # no copy happened


def test_cow_shared_page_allocates_private_copy():
    pool, p1, p2 = _share_scene()
    assert pool.needs_cow(2, 1)                    # page 0 of the prefix
    res = pool.cow_page(2, 1)
    src, dst = res
    assert src == p1[0] and dst not in p1
    assert pool.seq_pages(2)[0] == dst
    assert pool.seq_pages(1) == p1                 # donor untouched
    assert pool.page_ref(src) == 1 and pool.page_ref(dst) == 1
    # a position past the table is never a COW hit
    assert not pool.needs_cow(2, 4 * 10)


def test_cow_pool_dry_returns_sentinel():
    pool = _pool(n_pages=4, page_size=4, max_pages_per_seq=3)
    pool.alloc_seq(1, 4)
    pool.register_prefix(1, [1, 2, 3, 4])
    shared = pool.match_prefix([1, 2, 3, 4, 5])
    pool.alloc_seq_shared(2, shared, 5)
    pool.alloc_seq(3, 4)                           # drains the pool
    assert pool.pages_free == 0
    assert pool.cow_page(2, 0) == -1               # caller preempts


# ---------------------------------------------------------------------------
# snapshot: the dump_kv contract
# ---------------------------------------------------------------------------
def test_snapshot_is_json_ready_and_renders():
    import json

    from tools.dump_kv import render_snapshot

    pool, p1, p2 = _share_scene()
    snap = json.loads(json.dumps(pool.snapshot()))
    assert snap["pages_shared"] == 3
    assert snap["seqs"]["2"][:3] == p1
    assert all(snap["refs"][str(p)] == 2 for p in p1)
    text = render_snapshot(snap)
    assert "shared (ref > 1)" in text and "seq 2" in text
