"""Fused linear+cross-entropy — TPU-only hardware checks: real Mosaic
lowering of the 2D-grid reduction idiom (output-ref accumulators
revisited across the inner vocab axis) and fwd+bwd numerics at the
real MLM-head scale. Self-gates; run with the default TPU env."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Mosaic lowering needs a real TPU backend")


def _data(n, h, v, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(n, h) * 0.2, jnp.float32),
            jnp.asarray(rng.randn(v, h) * 0.2, jnp.float32),
            jnp.asarray(rng.randn(v) * 0.1, jnp.float32),
            jnp.asarray(rng.randint(0, v, n), jnp.int32))


def _ref_loss(h, w, b, lab):
    logits = h @ w.T + b
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
    return jnp.mean(-ll)


def test_fused_xent_lowers_and_matches_xla():
    from paddle_tpu.ops.pallas import counters
    from paddle_tpu.ops.pallas.fused_xent import (
        fused_linear_cross_entropy,
    )

    h, w, b, lab = _data(1024, 768, 30592)
    counters.reset()
    out = fused_linear_cross_entropy(h, w, b, lab)
    assert counters.snapshot().get("fused_xent.pallas", 0) == 1, (
        counters.snapshot())
    ref = _ref_loss(h, w, b, lab)
    np.testing.assert_allclose(float(out), float(ref), rtol=5e-4)


def test_fused_xent_bwd_lowers_and_matches_xla():
    from paddle_tpu.ops.pallas.fused_xent import (
        fused_linear_cross_entropy,
    )

    h, w, b, lab = _data(512, 768, 30592, seed=1)
    gf = jax.grad(lambda *a: fused_linear_cross_entropy(*a, lab),
                  argnums=(0, 1, 2))(h, w, b)
    gr = jax.grad(lambda *a: _ref_loss(*a, lab), argnums=(0, 1, 2))(h, w,
                                                                    b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-4)
