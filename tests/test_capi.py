"""C inference API tests (reference inference/capi/ +
inference/capi_tester.cc pattern): exercise the embedded-CPython C API
both in-process via ctypes and from a real compiled C client."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static import InputSpec

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 3)

    def forward(self, x):
        return self.fc(x)


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    paddle.seed(0)
    net = _Net()
    net.eval()
    prefix = str(tmp_path_factory.mktemp("capi") / "model")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([2, 4], "float32")])
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    expect = net(paddle.to_tensor(x)).numpy()
    return prefix, x, expect


def test_capi_in_process(saved_model):
    from paddle_tpu.native import capi_lib

    prefix, x, expect = saved_model
    lib = capi_lib()
    assert lib is not None, "capi must build (g++ + libpython baked in)"
    p = lib.PD_NewPredictor(prefix.encode())
    assert p, lib.PD_GetLastError()
    try:
        n_in = lib.PD_GetInputNum(p)
        assert n_in == 1
        name = lib.PD_GetInputName(p, 0)
        assert name == b"x0"
        shape = (ctypes.c_int64 * 2)(2, 4)
        data = x.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        assert lib.PD_SetInputFloat(p, name, data, shape, 2) == 0
        assert lib.PD_Run(p) == 0, lib.PD_GetLastError()
        assert lib.PD_GetOutputNum(p) == 1
        out_data = ctypes.POINTER(ctypes.c_float)()
        out_shape = ctypes.POINTER(ctypes.c_int64)()
        out_ndim = ctypes.c_int()
        assert lib.PD_GetOutputFloat(p, 0, ctypes.byref(out_data),
                                     ctypes.byref(out_shape),
                                     ctypes.byref(out_ndim)) == 0
        dims = [out_shape[i] for i in range(out_ndim.value)]
        assert dims == [2, 3]
        got = np.ctypeslib.as_array(out_data, shape=(2, 3)).copy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    finally:
        lib.PD_DeletePredictor(p)


C_CLIENT = r"""
#include <stdio.h>
#include <stdlib.h>
#include <thread>
#include "paddle_tpu_capi.h"

// The predictor work runs on a WORKER thread after PD_Init on main —
// this is the real gate for the embedded-init GIL release: if
// ensure_helper leaves the main thread holding the GIL, the worker
// deadlocks in PyGILState_Ensure and the harness timeout kills us.
static int worker(const char* prefix) {
  PD_Predictor* p = PD_NewPredictor(prefix);
  if (!p) { fprintf(stderr, "new: %s\n", PD_GetLastError()); return 3; }
  float x[8]; int64_t shape[2] = {2, 4};
  for (int i = 0; i < 8; ++i) x[i] = (float)i;
  if (PD_SetInputFloat(p, PD_GetInputName(p, 0), x, shape, 2) != 0) return 4;
  if (PD_Run(p) != 0) { fprintf(stderr, "run: %s\n", PD_GetLastError()); return 5; }
  const float* out; const int64_t* oshape; int ondim;
  if (PD_GetOutputFloat(p, 0, &out, &oshape, &ondim) != 0) return 6;
  printf("ndim=%d shape=%lld,%lld\n", ondim,
         (long long)oshape[0], (long long)oshape[1]);
  for (int i = 0; i < 6; ++i) printf("%.6f ", out[i]);
  printf("\n");
  PD_DeletePredictor(p);
  return 0;
}

int main(int argc, char** argv) {
  if (PD_Init(argv[2]) != 0) {
    fprintf(stderr, "init: %s\n", PD_GetLastError());
    return 2;
  }
  int rc = 7;
  std::thread t([&] { rc = worker(argv[1]); });
  t.join();
  return rc;
}
"""


def test_capi_from_c_client(saved_model, tmp_path):
    from paddle_tpu.native import _BUILD, capi_build_flags, capi_lib

    prefix, x, expect = saved_model
    lib = capi_lib()
    assert lib is not None
    so = lib._name
    src = tmp_path / "client.c"
    src.write_text(C_CLIENT)
    exe = tmp_path / "client"
    inc = os.path.join(REPO, "paddle_tpu", "native", "include")
    cmd = ["g++", "-o", str(exe), str(src), f"-I{inc}", so,
           f"-Wl,-rpath,{_BUILD}"] + capi_build_flags()
    subprocess.run(cmd, check=True, capture_output=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([str(exe), prefix, REPO], capture_output=True,
                       text=True, timeout=240, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "ndim=2 shape=2,3"
    got = np.array([float(v) for v in lines[1].split()]).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_capi_run_from_worker_thread(saved_model):
    """The GIL must be released after embedded init so a second thread can
    drive the predictor (serving pattern)."""
    import threading

    from paddle_tpu.native import capi_lib

    prefix, x, expect = saved_model
    lib = capi_lib()
    result = {}

    def worker():
        p = lib.PD_NewPredictor(prefix.encode())
        if not p:
            result["err"] = lib.PD_GetLastError()
            return
        try:
            shape = (ctypes.c_int64 * 2)(2, 4)
            data = x.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            lib.PD_SetInputFloat(p, b"x0", data, shape, 2)
            if lib.PD_Run(p) != 0:
                result["err"] = lib.PD_GetLastError()
                return
            out_data = ctypes.POINTER(ctypes.c_float)()
            out_shape = ctypes.POINTER(ctypes.c_int64)()
            out_ndim = ctypes.c_int()
            lib.PD_GetOutputFloat(p, 0, ctypes.byref(out_data),
                                  ctypes.byref(out_shape),
                                  ctypes.byref(out_ndim))
            result["out"] = np.ctypeslib.as_array(
                out_data, shape=(2, 3)).copy()
        finally:
            lib.PD_DeletePredictor(p)

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "worker thread deadlocked (GIL not released?)"
    assert "err" not in result, result.get("err")
    np.testing.assert_allclose(result["out"], expect, rtol=1e-5, atol=1e-6)


def test_capi_trainer(tmp_path):
    """C trainer API: load a saved (main, startup) pair, train steps from
    C, loss decreases, save persistables (reference
    fluid/train/demo/demo_trainer.cc flow)."""
    import paddle_tpu.static as static
    from paddle_tpu.native import capi_lib

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 4])
        y = static.data("y", [-1, 1])
        pred = static.nn.fc(x, 1)
        loss = static.mean(static.square_error_cost(pred, y))
        static.SGD(learning_rate=0.05).minimize(loss)
    prog_dir = str(tmp_path / "train_prog")
    static.save_train_program(prog_dir, main, startup)
    loss_name = loss.name

    lib = capi_lib()
    assert lib is not None
    t = lib.PD_NewTrainer(prog_dir.encode())
    assert t, lib.PD_GetLastError()
    try:
        rng = np.random.RandomState(0)
        w_true = rng.randn(4, 1).astype(np.float32)
        losses = []
        shape_x = (ctypes.c_int64 * 2)(16, 4)
        shape_y = (ctypes.c_int64 * 2)(16, 1)
        fetches = (ctypes.c_char_p * 1)(loss_name.encode())
        for step in range(30):
            xb = rng.randn(16, 4).astype(np.float32)
            yb = (xb @ w_true).astype(np.float32)
            assert lib.PD_TrainerSetInputFloat(
                t, b"x", xb.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                shape_x, 2) == 0
            assert lib.PD_TrainerSetInputFloat(
                t, b"y", yb.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                shape_y, 2) == 0
            assert lib.PD_TrainerRun(t, fetches, 1) == 0, \
                lib.PD_GetLastError()
            out = ctypes.POINTER(ctypes.c_float)()
            shp = ctypes.POINTER(ctypes.c_int64)()
            nd = ctypes.c_int()
            assert lib.PD_TrainerGetFetchFloat(
                t, 0, ctypes.byref(out), ctypes.byref(shp),
                ctypes.byref(nd)) == 0
            losses.append(float(out[0]))
        assert losses[-1] < losses[0] * 0.2, losses[:3] + losses[-3:]
        save_dir = str(tmp_path / "saved")
        assert lib.PD_TrainerSave(t, save_dir.encode()) == 0
        assert os.listdir(save_dir)
    finally:
        lib.PD_DeleteTrainer(t)
