"""Fused sampling (ops/pallas/sampling.py) in interpret mode
(CPU-hermetic): kernel parity against the XLA reference, greedy
short-circuit, top-k/top-p truncation semantics, dispatch counters,
the PADDLE_FUSED_SAMPLING=0 escape leg, and the autotune cache keys —
the same coverage contract the paged_attention kernel carries."""
import functools

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.framework.bringup as bringup
from paddle_tpu.ops.pallas import autotune, counters
from paddle_tpu.ops.pallas import sampling as sm


@pytest.fixture(autouse=True)
def interpret_pallas(monkeypatch):
    """Run pallas_call in interpret mode so kernels execute on CPU."""
    from jax.experimental import pallas as pl

    real = pl.pallas_call
    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(real, interpret=True))
    yield


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


def _rows(b=4, v=128, seed=0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(b, v) * 3, jnp.float32)
    noise = jnp.asarray(-np.log(-np.log(
        rng.uniform(1e-9, 1.0, (b, v)))), jnp.float32)
    return logits, noise


def test_temperature_zero_is_pure_argmax():
    """T <= 0 short-circuits to greedy and IGNORES the noise — the
    spec-decode-compatible leg."""
    logits, noise = _rows()
    out = np.asarray(sm.fused_sample(logits, noise, 0.0))
    assert (out == np.asarray(jnp.argmax(logits, -1))).all()
    out2 = np.asarray(sm.fused_sample(logits, noise * 100, 0.0))
    assert (out == out2).all()


@pytest.mark.parametrize("top_k", [0, 1, 4, 8])
def test_kernel_matches_xla_reference(top_k):
    logits, noise = _rows(seed=top_k)
    ref = np.asarray(sm._xla_sample(logits, noise, 0.7, top_k, 1.0))
    out = np.asarray(sm._fused_sample_pallas(logits, noise, 0.7, top_k))
    assert (out == ref).all()
    assert ((0 <= out) & (out < logits.shape[1])).all()


def test_top_k_truncates_support():
    """With top_k=2 the draw must land on one of the two largest
    logits no matter how hard the noise pulls elsewhere."""
    logits, _ = _rows(b=2, seed=3)
    order = np.argsort(-np.asarray(logits), axis=-1)
    # noise that screams for the WORST token
    noise = np.zeros(logits.shape, np.float32)
    for r in range(2):
        noise[r, order[r, -1]] = 1e4
    noise = jnp.asarray(noise)
    for fn in (lambda: sm._xla_sample(logits, noise, 1.0, 2, 1.0),
               lambda: sm._fused_sample_pallas(logits, noise, 1.0, 2)):
        out = np.asarray(fn())
        for r in range(2):
            assert out[r] in order[r, :2], (r, out[r], order[r, :4])


def test_top_p_truncates_support():
    """A peaked distribution under small top_p keeps only the head."""
    logits = jnp.asarray([[10.0, 9.9, -10.0, -10.0] + [-30.0] * 124],
                         jnp.float32)
    noise = jnp.zeros_like(logits).at[0, 2].set(1e4)
    out = np.asarray(sm._xla_sample(logits, noise, 1.0, 0, 0.9))
    assert out[0] in (0, 1)


def test_gumbel_max_matches_softmax_frequencies():
    """The Gumbel-max draw really samples softmax(logits/T): empirical
    frequencies over many iid noise rows track the analytic
    probabilities."""
    rng = np.random.RandomState(0)
    logits = jnp.asarray(np.tile([[2.0, 1.0, 0.0, -1.0] + [-30.0] * 124],
                                 (512, 1)), jnp.float32)
    noise = jnp.asarray(-np.log(-np.log(
        rng.uniform(1e-9, 1.0, (512, 128)))), jnp.float32)
    out = np.asarray(sm._xla_sample(logits, noise, 1.0, 0, 1.0))
    z = np.exp([2.0, 1.0, 0.0, -1.0])
    p = z / z.sum()
    freq = np.bincount(out, minlength=128)[:4] / 512
    np.testing.assert_allclose(freq, p, atol=0.08)


# ---------------------------------------------------------------------------
# dispatch: counters, gate, escape, autotune keys
# ---------------------------------------------------------------------------
def test_dispatch_pallas_bumps_counter(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    logits, noise = _rows()
    out = np.asarray(sm.fused_sample(logits, noise, 0.8, top_k=4))
    ref = np.asarray(sm._xla_sample(logits, noise, 0.8, 4, 1.0))
    assert (out == ref).all()
    assert counters.snapshot().get("fused_sample.pallas", 0) == 1


def test_top_p_routes_to_xla_with_reason(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    logits, noise = _rows()
    sm.fused_sample(logits, noise, 0.8, top_k=0, top_p=0.9)
    snap = counters.snapshot()
    assert snap.get("fused_sample.xla", 0) == 1
    assert snap.get("fused_sample.pallas", 0) == 0


def test_ineligible_vocab_falls_back(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    logits, noise = _rows(v=100)                   # V % 128 != 0
    sm.fused_sample(logits, noise, 0.8)
    assert counters.snapshot().get("fused_sample.xla", 0) == 1


def test_kernel_error_falls_back(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)

    def boom(*a, **k):
        raise RuntimeError("mosaic said no")

    monkeypatch.setattr(sm, "_fused_sample_pallas", boom)
    logits, noise = _rows()
    out = np.asarray(sm.fused_sample(logits, noise, 0.8, top_k=2))
    ref = np.asarray(sm._xla_sample(logits, noise, 0.8, 2, 1.0))
    assert (out == ref).all()
    assert counters.snapshot().get("fused_sample.xla", 0) == 1


def test_escape_env_pins_xla_bitwise(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    monkeypatch.setenv("PADDLE_FUSED_SAMPLING", "0")
    logits, noise = _rows()
    out = np.asarray(sm.fused_sample(logits, noise, 0.8, top_k=4))
    ref = np.asarray(sm._xla_sample(logits, noise, 0.8, 4, 1.0))
    assert out.tobytes() == ref.tobytes()
    snap = counters.snapshot()
    assert snap.get("fused_sample.pallas", 0) == 0
    assert snap.get("fused_sample.xla", 0) == 1


def test_sample_ok_gate(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    logits, _ = _rows()
    assert sm._sample_ok(logits, 0, 1.0)
    assert sm._sample_ok(logits, sm._KERNEL_TOPK_MAX, 1.0)
    assert not sm._sample_ok(logits, sm._KERNEL_TOPK_MAX + 1, 1.0)
    assert not sm._sample_ok(logits, 0, 0.95)
    big, _ = _rows(b=1, v=128 * 256)               # past the VMEM cap
    assert not sm._sample_ok(big, 0, 1.0)
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: False)
    assert not sm._sample_ok(logits, 0, 1.0)


def test_sample_cache_key_namespaced():
    key = autotune.sample_cache_key(4, 128, jnp.float32, 4)
    assert "sample" in str(key)
    assert key != autotune.sample_cache_key(4, 128, jnp.float32, 8)
    assert key != autotune.sample_cache_key(8, 128, jnp.float32, 4)
