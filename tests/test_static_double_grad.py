"""Static-graph double grad: calc_gradient of a calc_gradient output
(reference backward.py:1665 calc_gradient supports differentiating
through gradient ops; grad-var names uniquify like _rename_grad_ so the
second gradient cannot clobber the first)."""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers as L


def test_calc_gradient_twice_polynomial():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [3])
        x.stop_gradient = False
        y = L.reduce_sum(L.square(L.square(x)))      # sum(x^4)
        (dx,) = static.calc_gradient(y, [x])         # 4x^3
        z = L.reduce_sum(L.square(dx))               # sum(16 x^6)
        (ddx,) = static.calc_gradient(z, [x])        # 96 x^5
    assert dx.name != ddx.name, "second grad must not clobber the first"
    exe = static.Executor()
    exe.run(startup)
    xv = np.array([1.0, 2.0, 0.5], np.float32)
    gdx, gddx = exe.run(main, feed={"x": xv}, fetch_list=[dx, ddx])
    np.testing.assert_allclose(gdx, 4 * xv ** 3, rtol=1e-5)
    np.testing.assert_allclose(gddx, 96 * xv ** 5, rtol=1e-4)


def test_static_gradient_penalty_into_params():
    """The WGAN-GP static pattern: penalty on ||d out/d x|| trains the
    layer's parameters (second-order flow through fc)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 3])
        x.stop_gradient = False
        out = L.reduce_sum(L.fc(x, size=1))
        (gx,) = static.calc_gradient(out, [x])
        penalty = L.reduce_sum(L.square(gx))
        params = main.all_parameters()
        grads = static.calc_gradient(penalty, params)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    outs = exe.run(main, feed={"x": rng.randn(4, 3).astype(np.float32)},
                   fetch_list=list(grads))
    # d penalty / d W = 2 * N * W (gx = W^T per row) — nonzero, finite
    for g in outs:
        assert np.isfinite(g).all()
    assert any(np.abs(g).sum() > 0 for g in outs)
