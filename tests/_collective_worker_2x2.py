"""Worker for the 4-process 2x2 (dp x tp) mesh test — launched through
paddle_tpu.distributed.launch's start_local_trainers (reference
fleet/launch_utils.py:351), NOT hand-spawned. Reads the standard
PADDLE_* env the launcher wires, uses endpoint 0 as the jax.distributed
coordinator, builds a dp2 x tp2 mesh over the 4 single-device
processes, and runs a jitted train step where X rides dp and the MLP's
hidden dimension rides tp — XLA inserts the cross-process collectives.
Writes per-step losses to $PADDLE_TEST_OUT/losses_rank{r}.json.
"""
import json
import os
import sys

# scrub the parent test-process env BEFORE jax import: the pytest
# conftest forces 8 virtual devices per process, which would give this
# 4-process job 32 global devices instead of 4
os.environ.pop("XLA_FLAGS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.framework.bringup import force_cpu  # noqa: E402

force_cpu()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
    endpoints = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert nproc == 4, nproc

    from paddle_tpu.distributed import get_rank, init_distributed

    init_distributed(endpoints[0], nproc, rank)
    assert get_rank() == rank
    assert jax.device_count() == nproc, jax.device_count()

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2), ("dp", "tp"))

    rng = np.random.RandomState(0)
    per = 4                       # batch shard per dp group
    dp = 2
    X = rng.randn(per * dp, 4).astype(np.float32)
    Y = rng.randn(per * dp, 1).astype(np.float32)
    W1 = rng.randn(4, 8).astype(np.float32) * 0.5
    W2 = rng.randn(8, 1).astype(np.float32) * 0.5

    x_shard = NamedSharding(mesh, P("dp", None))
    w1_shard = NamedSharding(mesh, P(None, "tp"))   # hidden dim on tp
    w2_shard = NamedSharding(mesh, P("tp", None))

    dp_group = rank // 2          # devices laid out (dp, tp) row-major
    gx = jax.make_array_from_process_local_data(
        x_shard, X[dp_group * per:(dp_group + 1) * per])
    gy = jax.make_array_from_process_local_data(
        x_shard, Y[dp_group * per:(dp_group + 1) * per])
    gw1 = jax.device_put(W1, w1_shard)
    gw2 = jax.device_put(W2, w2_shard)

    @jax.jit
    def step(w1, w2, x, y):
        def loss_fn(params):
            w1, w2 = params
            h = jax.nn.relu(x @ w1)
            return jnp.mean((h @ w2 - y) ** 2)

        loss, (g1, g2) = jax.value_and_grad(loss_fn)((w1, w2))
        return loss, w1 - 0.1 * g1, w2 - 0.1 * g2

    losses = []
    for _ in range(3):
        loss, gw1, gw2 = step(gw1, gw2, gx, gy)
        losses.append(float(loss))

    out_dir = os.environ["PADDLE_TEST_OUT"]
    with open(os.path.join(out_dir, f"losses_rank{rank}.json"), "w") as f:
        json.dump(losses, f)
    print(f"DONE {rank}", flush=True)


if __name__ == "__main__":
    main()
