"""Vision/text dataset additions: Cifar, Flowers, VOC2012, folder
loaders, WMT14, MovieReviews (reference incubate/hapi/datasets/*), and
MobileNetV1."""
import pickle
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets as vdatasets


def test_cifar10_archive_roundtrip(tmp_path):
    """File mode parses the cifar-10-python pickle-batch tar layout
    (reference cifar.py _load_data)."""
    rng = np.random.RandomState(0)
    root = tmp_path / "cifar-10-batches-py"
    root.mkdir()
    for name, n in [("data_batch_1", 6), ("data_batch_2", 4),
                    ("test_batch", 3)]:
        batch = {b"data": rng.randint(0, 256, (n, 3072)).astype(np.uint8),
                 b"labels": rng.randint(0, 10, n).tolist()}
        with open(root / name, "wb") as f:
            pickle.dump(batch, f)
    tar_path = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(root, arcname="cifar-10-batches-py")

    train = vdatasets.Cifar10(str(tar_path), mode="train")
    test = vdatasets.Cifar10(str(tar_path), mode="test")
    assert len(train) == 10 and len(test) == 3
    img, label = train[0]
    assert img.shape == (3, 32, 32) and img.dtype == np.float32
    assert 0 <= int(label) < 10


def test_cifar_synthetic_schema():
    c10 = vdatasets.Cifar10()
    c100 = vdatasets.Cifar100(mode="test")
    img, label = c10[0]
    assert img.shape == (3, 32, 32)
    assert img.min() >= 0.0 and img.max() <= 1.0
    assert c100.labels.max() < 100
    # deterministic across constructions
    again, _ = vdatasets.Cifar10()[0]
    np.testing.assert_array_equal(img, again)


def test_flowers_and_voc_synthetic():
    f = vdatasets.Flowers(mode="train", image_size=(32, 32))
    img, label = f[3]
    assert img.shape == (32, 32, 3) and label.shape == (1,)
    assert 1 <= int(label[0]) <= 102
    v = vdatasets.VOC2012(mode="valid", image_size=(32, 32))
    img, mask = v[1]
    assert img.shape == (32, 32, 3) and mask.shape == (32, 32)
    assert mask.max() <= 20


def _write_npy_tree(root, classes, per_class):
    rng = np.random.RandomState(1)
    for cls in classes:
        d = root / cls
        d.mkdir(parents=True)
        for i in range(per_class):
            np.save(d / f"{i}.npy", rng.rand(4, 4, 3).astype(np.float32))


def test_dataset_folder(tmp_path):
    _write_npy_tree(tmp_path, ["cat", "dog"], 3)
    ds = vdatasets.DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    sample, target = ds[0]
    assert sample.shape == (4, 4, 3) and int(target) == 0
    assert int(ds[5][1]) == 1
    with pytest.raises(RuntimeError):
        empty = tmp_path / "empty"
        empty.mkdir()
        vdatasets.DatasetFolder(str(empty))


def test_image_folder(tmp_path):
    _write_npy_tree(tmp_path, ["unlabelled"], 4)
    ds = vdatasets.ImageFolder(str(tmp_path))
    assert len(ds) == 4
    (sample,) = ds[2]
    assert sample.shape == (4, 4, 3)


def test_wmt14_schema():
    from paddle_tpu.text import WMT14
    ds = WMT14(dict_size=200, synthetic_size=32)
    src, trg_in, trg_out = ds[0]
    assert trg_in[0] == ds.BOS and trg_out[-1] == ds.EOS
    assert len(trg_in) == len(trg_out)
    np.testing.assert_array_equal(trg_in[1:], trg_out[:-1])
    assert src.max() < 200 and trg_in.max() < 200
    # deterministic across constructions
    src2, _, _ = WMT14(dict_size=200, synthetic_size=32)[0]
    np.testing.assert_array_equal(src, src2)


def test_movie_reviews(tmp_path):
    from paddle_tpu.text import MovieReviews
    syn = MovieReviews(synthetic_size=16)
    ids, label = syn[0]
    assert ids.dtype == np.int64 and int(label) in (0, 1)
    path = tmp_path / "reviews.tsv"
    path.write_text("1\tgreat film truly great\n0\tawful boring mess\n")
    ds = MovieReviews(str(path), vocab_size=100)
    assert len(ds) == 2
    assert int(ds[0][1]) == 1 and int(ds[1][1]) == 0
    assert ds[0][0].max() < 100


@pytest.mark.slow
def test_mobilenet_v1_trains():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models import mobilenet_v1

    paddle.seed(0)
    net = mobilenet_v1(num_classes=4, scale=0.25)
    opt = optimizer.Momentum(learning_rate=0.1,
                             parameters=net.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(4, 3, 32, 32).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (4,)).astype(np.int64))
    losses = []
    for _ in range(3):
        logits = net(x)
        loss = nn.functional.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
