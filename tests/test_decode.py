"""Seq2seq decoding API (nn/decode.py vs reference fluid/layers/rnn.py:
BeamSearchDecoder semantics, dynamic_decode loop, helper family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.decode import (BasicDecoder, BeamSearchDecoder,
                                  GreedyEmbeddingHelper,
                                  SampleEmbeddingHelper, TrainingHelper,
                                  dynamic_decode)


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


class RiggedCell:
    """A 'cell' whose logits follow a fixed per-step script, so the
    best decode path is known in closed form. States: a (batch, 1)
    step counter."""

    def __init__(self, script, vocab):
        # script: list of token ids, the forced argmax at each step
        self.script = script
        self.vocab = vocab

    def __call__(self, inputs, states, **kw):
        import jax.numpy as jnp
        step_arr = _np(states)
        t = int(step_arr.reshape(-1)[0])
        tok = self.script[min(t, len(self.script) - 1)]
        logits = np.full((step_arr.shape[0], self.vocab), -5.0, np.float32)
        logits[:, tok] = 5.0
        from paddle_tpu.framework.tensor import Tensor
        return (Tensor(jnp.asarray(logits)),
                Tensor(jnp.asarray(step_arr + 1)))


def test_beam_search_decoder_follows_rigged_script():
    vocab, beam, batch = 7, 3, 2
    end = 0
    script = [4, 2, 5, end]
    dec = BeamSearchDecoder(RiggedCell(script, vocab), start_token=1,
                            end_token=end, beam_size=beam)
    import jax.numpy as jnp
    init_states = jnp.zeros((batch, 1), jnp.int64)
    outputs, final_states, seq_len = dynamic_decode(
        dec, inits=init_states, max_step_num=10, return_length=True)
    ids = _np(outputs)                      # (batch, time, beam)
    assert ids.shape[0] == batch
    # the top beam must follow the scripted path then the end token
    top = ids[0, :, 0].tolist()
    assert top[:4] == script
    # all beams finished at the end token -> loop exited early (<=10)
    assert ids.shape[1] <= 6
    lengths = _np(seq_len)
    assert lengths.shape == (batch, beam)
    assert int(lengths[0, 0]) == 4          # 4 real tokens incl. end


@pytest.mark.slow
def test_beam_search_decoder_with_lstm_and_embedding():
    vocab, hidden, beam, batch = 11, 16, 4, 3
    np.random.seed(0)
    emb = nn.Embedding(vocab, hidden)
    cell = nn.LSTMCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    dec = BeamSearchDecoder(cell, start_token=1, end_token=2,
                            beam_size=beam, embedding_fn=emb,
                            output_fn=proj)
    import jax.numpy as jnp
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    c0 = jnp.zeros((batch, hidden), jnp.float32)
    outputs, final_states = dynamic_decode(dec, inits=(h0, c0),
                                           max_step_num=5)
    ids = _np(outputs)
    assert ids.shape[0] == batch and ids.shape[2] == beam
    assert ids.shape[1] <= 6
    assert ids.dtype in (np.int64, np.int32)
    # log probs are finite and sorted descending across beams at exit
    lp = np.asarray(final_states.log_probs)
    assert np.isfinite(lp[:, 0]).all()
    assert (np.diff(lp, axis=1) <= 1e-5).all()


def test_tile_beam_merge_with_batch():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    t = _np(BeamSearchDecoder.tile_beam_merge_with_batch(x, 2))
    assert t.shape == (4, 3)
    np.testing.assert_allclose(t[0], t[1])
    np.testing.assert_allclose(t[2], t[3])


def test_basic_decoder_greedy_helper():
    vocab, hidden, batch = 9, 8, 2
    emb = nn.Embedding(vocab, hidden)
    cell = nn.GRUCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    import jax.numpy as jnp
    helper = GreedyEmbeddingHelper(emb, jnp.ones((batch,), jnp.int64), 0)
    dec = BasicDecoder(cell, helper, output_fn=proj)
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    outputs, _ = dynamic_decode(dec, inits=h0, max_step_num=4)
    logits = _np(outputs.cell_outputs)      # (batch, time, vocab)
    ids = _np(outputs.sample_ids)           # (batch, time)
    assert logits.shape[0] == batch and logits.shape[2] == vocab
    # sample_ids ARE the argmax of the emitted logits (greedy contract)
    np.testing.assert_array_equal(ids, logits.argmax(-1))


def test_basic_decoder_training_helper_teacher_forcing():
    vocab, hidden, batch, T = 6, 8, 2, 5
    np.random.seed(1)
    cell = nn.SimpleRNNCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    import jax.numpy as jnp
    gt = jnp.asarray(np.random.randn(batch, T, hidden), jnp.float32)
    seq_len = jnp.asarray([T, 3])
    helper = TrainingHelper(gt, seq_len)
    dec = BasicDecoder(cell, helper, output_fn=proj)
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    outputs, _, lengths = dynamic_decode(dec, inits=h0, max_step_num=T,
                                         return_length=True)
    ids = _np(outputs.sample_ids)
    assert ids.shape == (batch, T)          # runs to the longest length
    ln = _np(lengths)
    assert ln[0] == T and ln[1] == 3


def test_sample_embedding_helper_respects_temperature():
    vocab, hidden, batch = 8, 8, 4
    emb = nn.Embedding(vocab, hidden)
    cell = nn.GRUCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    import jax.numpy as jnp
    helper = SampleEmbeddingHelper(emb, jnp.ones((batch,), jnp.int64), 0,
                                   softmax_temperature=0.5, seed=3)
    dec = BasicDecoder(cell, helper, output_fn=proj)
    h0 = jnp.zeros((batch, hidden), jnp.float32)
    outputs, _ = dynamic_decode(dec, inits=h0, max_step_num=3)
    ids = _np(outputs.sample_ids)
    assert ids.min() >= 0 and ids.max() < vocab


def test_layers_facades_and_rnn():
    from paddle_tpu.static import layers as L
    for n in ("Decoder", "BeamSearchDecoder", "BasicDecoder",
              "DecodeHelper", "TrainingHelper", "GreedyEmbeddingHelper",
              "SampleEmbeddingHelper", "dynamic_decode", "rnn"):
        assert hasattr(L, n), n
    # layers.rnn scans a cell over time
    import jax.numpy as jnp
    cell = nn.GRUCell(4, 4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 4), np.float32)
    outs, final = L.rnn(cell, x)
    assert _np(outs).shape == (2, 5, 4)
