"""Distributed loss-parity tests — the reference's core distributed test
criterion (/root/reference/python/paddle/fluid/tests/unittests/
test_dist_base.py:933 check_with_place: a distributed run's losses must
match the single-process run within delta).

Here the "cluster" is the virtual 8-device CPU mesh (conftest), and the
parity is exact math: a dp-sharded TrainStep consumes the same global
batch as the single-device step, so the allreduced gradients must match.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.parallel import create_mesh
from jax.sharding import PartitionSpec


pytestmark = pytest.mark.slow

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _loss_fn(m, x, y):
    return nn.functional.cross_entropy(m(x), y)


def _train(mesh=None, data_spec=None, steps=5):
    paddle.seed(7)
    model = _MLP()
    opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=model.parameters())
    kw = {}
    if mesh is not None:
        kw = dict(mesh=mesh, data_spec=data_spec)
    step = TrainStep(model, _loss_fn, opt, **kw)
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, 32, 16).astype("float32")
    ys = (xs.sum(-1) > 0).astype("int64") % 4
    losses = []
    for t in range(steps):
        losses.append(float(step(paddle.to_tensor(xs[t]),
                                 paddle.to_tensor(ys[t]))))
    return losses, {n: np.asarray(p.value)
                    for n, p in model.named_parameters()}


def test_dp8_loss_parity_with_single_device():
    single_losses, single_params = _train()
    mesh = create_mesh({"dp": 8})
    dp_losses, dp_params = _train(mesh=mesh,
                                  data_spec=PartitionSpec("dp"))
    np.testing.assert_allclose(dp_losses, single_losses, rtol=1e-4,
                               atol=1e-5)
    for n in single_params:
        np.testing.assert_allclose(dp_params[n], single_params[n],
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"param {n} diverged under dp")


def test_dp_losses_decrease():
    mesh = create_mesh({"dp": 8})
    losses, _ = _train(mesh=mesh, data_spec=PartitionSpec("dp"), steps=10)
    assert losses[-1] < losses[0]


def test_tp_sp_loss_parity_with_single_device():
    """Tensor + sequence parallel BERT step must track the single-device
    loss (the reference's NCCL2-mode parity check, extended to the
    parallelisms the reference lacked)."""
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.parallel import set_mesh
    from paddle_tpu.parallel.sharding import TRANSFORMER_TP_RULES

    def build():
        paddle.seed(11)
        cfg = BertConfig.tiny()
        cfg.attention_probs_dropout_prob = 0.0
        cfg.hidden_dropout_prob = 0.0
        model = BertForPretraining(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        return cfg, model, opt

    def data(cfg):
        rng = np.random.RandomState(3)
        b, L = 4, 32
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (b, L)).astype(np.int32))
        tt = paddle.to_tensor(np.zeros((b, L), np.int32))
        mlm = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (b, L)).astype(np.int32))
        nsp = paddle.to_tensor(rng.randint(0, 2, (b,)).astype(np.int32))
        return ids, tt, mlm, nsp

    def loss_fn(m, ids, tt, mlm, nsp):
        return m.loss(ids, tt, mlm, nsp)

    cfg, model, opt = build()
    step = TrainStep(model, loss_fn, opt)
    batch = data(cfg)
    ref = [float(step(*batch)) for _ in range(3)]

    cfg, model, opt = build()
    mesh = create_mesh({"tp": 2, "sp": 2, "dp": 2})
    set_mesh(mesh)
    try:
        step = TrainStep(model, loss_fn, opt, mesh=mesh,
                         param_rules=TRANSFORMER_TP_RULES,
                         data_spec=PartitionSpec("dp", "sp"),
                         sequence_parallel="sp")
        got = [float(step(*batch)) for _ in range(3)]
    finally:
        set_mesh(None)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)
