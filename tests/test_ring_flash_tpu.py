"""Flash-ring attention — TPU-only hardware checks. Interpret mode
cannot vouch for Mosaic lowering (the r3 fused-embedding lesson), and
the flash-ring composition is novel on the chip: pallas_call inside
lax.switch inside fori_loop inside shard_map, with vma-typed out_shapes.

One real chip cannot rotate a >1 ring, so the shard_map here is a
1-device mesh: the custom_vjp, the switch diagonal branch, and both
backward kernels still lower and execute for real; multi-device
numerics are pinned by tests/test_ring_flash.py on the 8-device CPU
mesh. Self-gates; run with the default TPU env.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Mosaic lowering needs a real TPU backend")


def _mesh1():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:1]), ("sp",))


def _qkv(l=256, b=2, h=4, d=64, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, l, h, d) * 0.5, jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_lowers_and_matches_xla(causal):
    from jax.sharding import PartitionSpec

    from paddle_tpu.ops.pallas.flash_attention import _xla_attention
    from paddle_tpu.parallel.ring import _ring_flash

    q, k, v = _qkv()
    spec = PartitionSpec(None, "sp", None, None)

    def local(q_, k_, v_):
        bias = jnp.zeros((), jnp.float32)
        return _ring_flash(q_, k_, v_, bias, "sp", 1, causal, False)

    out = jax.shard_map(local, mesh=_mesh1(), in_specs=(spec,) * 3,
                        out_specs=spec)(q, k, v)
    ref = _xla_attention(q, k, v, None, 0.0, causal, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_flash_bwd_lowers_and_matches_xla():
    from jax.sharding import PartitionSpec

    from paddle_tpu.ops.pallas.flash_attention import _xla_attention
    from paddle_tpu.parallel.ring import _ring_flash

    q, k, v = _qkv(seed=1)
    spec = PartitionSpec(None, "sp", None, None)

    def loss_ring(q_, k_, v_):
        def local(a, b_, c):
            bias = jnp.zeros((), jnp.float32)
            return _ring_flash(a, b_, c, bias, "sp", 1, True, False)

        out = jax.shard_map(local, mesh=_mesh1(), in_specs=(spec,) * 3,
                            out_specs=spec)(q_, k_, v_)
        return jnp.sum(out ** 2)

    def loss_x(q_, k_, v_):
        return jnp.sum(_xla_attention(q_, k_, v_, None, 0.0, True,
                                      None) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_ring_flash_masked_lowers():
    from jax.sharding import PartitionSpec

    from paddle_tpu.ops.pallas.flash_attention import _xla_attention
    from paddle_tpu.parallel.ring import _ring_flash

    q, k, v = _qkv(seed=2)
    b, l = q.shape[0], q.shape[1]
    mask = np.random.RandomState(3).rand(b, l) > 0.3
    mask[:, :32] = True
    bias = jnp.where(jnp.asarray(mask), 0.0, -1e30).astype(jnp.float32)
    spec = PartitionSpec(None, "sp", None, None)
    bspec = PartitionSpec(None, "sp")

    def local(q_, k_, v_, bias_):
        return _ring_flash(q_, k_, v_, bias_, "sp", 1, False, True)

    out = jax.shard_map(local, mesh=_mesh1(),
                        in_specs=(spec, spec, spec, bspec),
                        out_specs=spec)(q, k, v, bias)
    ref = _xla_attention(q, k, v, jnp.asarray(mask)[:, None, None, :],
                         0.0, False, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
