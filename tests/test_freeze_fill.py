"""Behavior checks for the surface gaps the namespace freeze exposed
(VERDICT r3 missing #3 follow-through): the new names must compute, not
just resolve."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.nn import functional as F
from paddle_tpu.static import layers as L


def _run_static(build, feeds):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        outs = build()
    exe = static.Executor()
    exe.run(startup)
    return exe.run(main, feed=feeds,
                   fetch_list=outs if isinstance(outs, list) else [outs])


def test_activation_tail_values():
    x = np.linspace(-2, 2, 9).astype(np.float32)

    def build():
        v = static.data("x", [9])
        return [L.logsigmoid(v), L.tanh_shrink(v), L.softshrink(v, 0.5),
                L.hard_shrink(v, 0.5), L.thresholded_relu(v, 1.0),
                L.cos(v), L.erf(v), L.cumsum(v)]

    ls, ts, ss, hs, tr, cos, erf, cs = _run_static(build, {"x": x})
    np.testing.assert_allclose(ls, np.log(1 / (1 + np.exp(-x))), rtol=1e-5)
    np.testing.assert_allclose(ts, x - np.tanh(x), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        ss, np.where(x > .5, x - .5, np.where(x < -.5, x + .5, 0)),
        rtol=1e-5)
    np.testing.assert_allclose(hs, np.where(np.abs(x) > .5, x, 0),
                               rtol=1e-5)
    np.testing.assert_allclose(tr, np.where(x > 1.0, x, 0), rtol=1e-5)
    np.testing.assert_allclose(cos, np.cos(x), rtol=1e-5)
    from scipy.special import erf as sp_erf
    np.testing.assert_allclose(erf, sp_erf(x), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(cs, np.cumsum(x), rtol=1e-5)


def test_cumsum_attrs():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)

    def build():
        v = static.data("x", [2, 3])
        return [L.cumsum(v, axis=1), L.cumsum(v, axis=1, exclusive=True),
                L.cumsum(v, axis=1, reverse=True)]

    a, e, r = _run_static(build, {"x": x})
    np.testing.assert_allclose(a, np.cumsum(x, 1))
    np.testing.assert_allclose(e, np.cumsum(x, 1) - x)
    np.testing.assert_allclose(r, np.flip(np.cumsum(np.flip(x, 1), 1), 1))


def test_static_save_load_roundtrip(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 4])
        y = L.fc(x, size=3)
    exe = static.Executor()
    exe.run(startup)
    path = str(tmp_path / "model" / "ckpt")
    static.save(main, path)
    import os
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdmodel")

    feed = {"x": np.ones((2, 4), np.float32)}
    (before,) = exe.run(main, feed=feed, fetch_list=[y])
    # clobber the scope, restore, re-run: outputs must match bit-exact
    scope = static.global_scope()
    for p in main.all_parameters():
        scope.set(p.name, np.zeros_like(np.asarray(scope.find_var(p.name))))
    (zeroed,) = exe.run(main, feed=feed, fetch_list=[y])
    assert not np.allclose(before, zeroed) or np.allclose(before, 0)
    static.load(main, path)
    (after,) = exe.run(main, feed=feed, fetch_list=[y])
    np.testing.assert_array_equal(before, after)


def test_functional_bilinear_and_cosine_similarity_grads():
    rng = np.random.RandomState(0)
    x1 = paddle.to_tensor(rng.randn(2, 3).astype(np.float32),
                          stop_gradient=False)
    x2 = paddle.to_tensor(rng.randn(2, 4).astype(np.float32))
    w = paddle.to_tensor(rng.randn(5, 3, 4).astype(np.float32))
    out = F.bilinear(x1, x2, w)
    assert tuple(out.shape) == (2, 5)
    exp = np.einsum("bi,kij,bj->bk", x1.numpy(), w.numpy(), x2.numpy())
    np.testing.assert_allclose(out.numpy(), exp, rtol=1e-4)
    out.sum().backward()
    assert x1.grad is not None and np.isfinite(x1.grad.numpy()).all()

    a = paddle.to_tensor(rng.randn(3, 6).astype(np.float32))
    b = paddle.to_tensor(rng.randn(3, 6).astype(np.float32))
    cs = F.cosine_similarity(a, b, axis=1)
    an, bn = a.numpy(), b.numpy()
    expc = (an * bn).sum(1) / (np.linalg.norm(an, axis=1)
                               * np.linalg.norm(bn, axis=1))
    np.testing.assert_allclose(cs.numpy(), expc, rtol=1e-5)


def test_conv_transpose_aliases():
    assert F.conv_transpose2d is F.conv2d_transpose
    assert F.conv_transpose3d is F.conv3d_transpose
    # fluid-surface name keeps fluid defaults (slope=0.2), distinct from
    # the 2.0 Hardsigmoid functional (slope 1/6)
    assert callable(F.hard_sigmoid) and F.hard_sigmoid is not F.hardsigmoid


def test_set_global_initializer():
    from paddle_tpu import nn
    from paddle_tpu.nn import initializer as I

    I.set_global_initializer(I.Constant(0.25), I.Constant(0.5))
    try:
        lin = nn.Linear(3, 2)
        np.testing.assert_allclose(lin.weight.numpy(), 0.25)
        np.testing.assert_allclose(lin.bias.numpy(), 0.5)
    finally:
        I.set_global_initializer(None, None)
    lin2 = nn.Linear(3, 2)
    assert not np.allclose(lin2.weight.numpy(), 0.25)


def test_numpy_array_initializer():
    from paddle_tpu.nn import initializer as I

    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    init = I.NumpyArrayInitializer(arr)
    np.testing.assert_allclose(np.asarray(init((2, 3), "float32")), arr)


def test_fashion_mnist_dataset():
    from paddle_tpu.hapi import datasets

    ds = datasets.FashionMNIST(mode="test", synthetic_size=64)
    img, label = ds[0]
    assert img.shape == (1, 28, 28)
    assert 0 <= int(label) < 10
    # distinguishable from the MNIST synthetic set (different base seed)
    mn = datasets.MNIST(mode="test", synthetic_size=64)
    assert not np.allclose(ds[0][0], mn[0][0])


def test_hapi_download_local_only(tmp_path):
    from paddle_tpu.hapi import download

    p = tmp_path / "w.bin"
    p.write_bytes(b"x")
    assert download.get_path_from_url(str(p)) == str(p)
    with pytest.raises(FileNotFoundError):
        download.get_weights_path_from_url("http://example.com/nope.bin")


def test_hapi_utils():
    from paddle_tpu.hapi import utils

    assert utils.to_list(1) == [1]
    assert utils.to_list(None) is None
    flat, st = utils.flatten_list([[1, 2], 3, [4]])
    assert flat == [1, 2, 3, 4]
    assert utils.restore_flatten_list(flat, st) == [[1, 2], 3, [4]]


def test_incubate_reexports():
    import paddle_tpu.incubate as inc

    assert inc.set_device is not None
    assert hasattr(inc.reader, "batch")
    assert inc.distributed.DistributedBatchSampler is not None


def test_metric_functional_ops_resolve():
    import paddle_tpu.metric as M

    for n in ("auc", "chunk_eval", "cos_sim", "mean_iou"):
        assert callable(getattr(M, n))
