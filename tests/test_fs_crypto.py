"""fs layer + encrypted-model-io tests (reference
framework/io/fs.cc, io/crypto/aes_cipher_test.cc, incubate fs.py tests)."""
import os

import numpy as np
import pytest

from paddle_tpu.io.crypto import (AESCipher, _ctr_py, gen_key,
                                  gen_key_to_file)
from paddle_tpu.io.fs import (ExecuteError, FSFileExistsError, HDFSClient,
                              LocalFS)


# FIPS-197 appendix C vectors
VEC128 = (bytes(range(16)), bytes.fromhex("00112233445566778899aabbccddeeff"),
          bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a"))
VEC256 = (bytes(range(32)), bytes.fromhex("00112233445566778899aabbccddeeff"),
          bytes.fromhex("8ea2b7ca516745bfeafc49904b496089"))
# NIST SP800-38A F.5.1 CTR-AES128 first block
CTR_KEY = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
CTR_IV = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
CTR_PT = bytes.fromhex("6bc1bee22e409f96e93d7e117393172a")
CTR_CT = bytes.fromhex("874d6191b620e3261bef6864990db6ce")


def test_native_block_matches_fips_vectors():
    from paddle_tpu.native import load_library
    import ctypes

    lib = load_library("aes")
    assert lib is not None, "native AES must build (g++ is baked in)"
    for key, pt, expect in (VEC128, VEC256):
        out = ctypes.create_string_buffer(16)
        rc = lib.pt_aes_encrypt_block(key, len(key), pt, out)
        assert rc == 0
        assert out.raw == expect


def test_python_ctr_matches_nist_vector():
    assert _ctr_py(CTR_KEY, CTR_IV, CTR_PT) == CTR_CT


def test_native_and_python_agree():
    key = bytes(range(32))
    iv = bytes(range(16))
    data = bytes(os.urandom(1000))
    c = AESCipher(key)
    native = c._ctr(iv, data)
    assert native == _ctr_py(key, iv, data)


def test_cipher_roundtrip_and_file(tmp_path):
    key = gen_key()
    c = AESCipher(key)
    msg = b"paddle_tpu encrypted checkpoint" * 100
    ct = c.encrypt(msg)
    assert ct[16:] != msg[:len(ct) - 16]
    assert c.decrypt(ct) == msg
    # wrong key fails to roundtrip
    assert AESCipher(gen_key()).decrypt(ct) != msg

    src = tmp_path / "model.pdparams"
    src.write_bytes(msg)
    enc = tmp_path / "model.enc"
    dec = tmp_path / "model.dec"
    c.encrypt_file(str(src), str(enc))
    c.decrypt_file(str(enc), str(dec))
    assert dec.read_bytes() == msg


def test_gen_key_to_file(tmp_path):
    p = tmp_path / "key.bin"
    key = gen_key_to_file(str(p))
    assert p.read_bytes() == key and len(key) == 32
    assert (os.stat(p).st_mode & 0o777) == 0o600


def test_bad_key_rejected():
    with pytest.raises(ValueError):
        AESCipher(b"short")


def test_local_fs(tmp_path):
    fs = LocalFS()
    d = tmp_path / "ckpt"
    fs.mkdirs(str(d))
    assert fs.is_dir(str(d)) and fs.is_exist(str(d))
    f = d / "a.txt"
    fs.touch(str(f))
    assert fs.is_file(str(f))
    with pytest.raises(FSFileExistsError):
        fs.touch(str(f), exist_ok=False)
    dirs, files = fs.ls_dir(str(d))
    assert files == ["a.txt"] and dirs == []
    fs.mv(str(f), str(d / "b.txt"))
    assert fs.is_file(str(d / "b.txt")) and not fs.is_exist(str(f))
    (d / "sub").mkdir()
    assert fs.list_dirs(str(d)) == ["sub"]
    fs.delete(str(d))
    assert not fs.is_exist(str(d))
    assert fs.need_upload_download() is False


def test_hdfs_client_command_construction():
    calls = []

    def fake_runner(args):
        calls.append(args)
        if args[0] == "-ls":
            return 0, [
                "Found 2 items",
                "drwxr-xr-x - u g 0 2026-01-01 00:00 hdfs://nn/a/dir1",
                "-rw-r--r-- 3 u g 9 2026-01-01 00:00 hdfs://nn/a/f1",
            ]
        return 0, []

    fs = HDFSClient(hadoop_home="/opt/hadoop",
                    configs={"fs.default.name": "hdfs://nn:9000"},
                    _runner=fake_runner)
    dirs, files = fs.ls_dir("hdfs://nn/a")
    assert dirs == ["dir1"] and files == ["f1"]
    assert fs.need_upload_download() is True
    fs.mkdirs("hdfs://nn/b")
    assert ["-mkdir", "-p", "hdfs://nn/b"] in calls
    base = fs._base_cmd()
    assert base[0] == "/opt/hadoop/bin/hadoop"
    assert "-D" in base and "fs.default.name=hdfs://nn:9000" in base


def test_hdfs_client_without_binary_errors():
    fs = HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(ExecuteError, match="no hadoop binary"):
        fs.is_exist("hdfs://nn/x")
