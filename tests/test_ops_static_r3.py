"""Round-3 OpTest batch: declarative output + numeric-grad checks for
static kernels that previously only had layer-level tests (losses,
activations, misc vision math). Reference fixture: unittests/op_test.py
— numpy forward reference + finite-difference grad parity."""
import numpy as np
import pytest

from op_test import OpTestCase

rng = np.random.RandomState(7)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


class TestHuberLoss(OpTestCase):
    op_type = "huber_loss_s"
    x = rng.randn(6, 3).astype(np.float32)
    y = rng.randn(6, 3).astype(np.float32)
    inputs = {"X": x, "Label": y}
    attrs = {"delta": 1.0}
    d = x - y
    outputs = {"Out": np.where(np.abs(d) <= 1.0, 0.5 * d * d,
                               np.abs(d) - 0.5).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestMseLoss(OpTestCase):
    op_type = "mse_loss_s"
    x = rng.randn(5, 4).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)
    inputs = {"X": x, "Label": y}
    outputs = {"Out": np.asarray(((x - y) ** 2).mean(), np.float32)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestLogLoss(OpTestCase):
    op_type = "log_loss_s"
    p = rng.uniform(0.1, 0.9, (8, 1)).astype(np.float32)
    label = rng.randint(0, 2, (8, 1)).astype(np.float32)
    inputs = {"Predicted": p, "Labels": label}
    attrs = {"epsilon": 1e-4}
    outputs = {"Out": (-label * np.log(p + 1e-4) -
                       (1 - label) * np.log(1 - p + 1e-4)
                       ).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["Predicted"])


class TestMarginRankLoss(OpTestCase):
    op_type = "margin_rank_loss_s"
    x1 = rng.randn(7, 1).astype(np.float32)
    x2 = rng.randn(7, 1).astype(np.float32)
    label = np.where(rng.rand(7, 1) > 0.5, 1.0, -1.0).astype(np.float32)
    inputs = {"Label": label, "Left": x1, "Right": x2}
    attrs = {"margin": 0.1}
    outputs = {"Out": np.maximum(0, -label * (x1 - x2) + 0.1
                                 ).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)


class TestLabelSmooth(OpTestCase):
    op_type = "label_smooth_s"
    onehot = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 6)]
    inputs = {"X": onehot}
    attrs = {"epsilon": 0.1}
    outputs = {"Out": ((1 - 0.1) * onehot + 0.1 / 5).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-6)


class TestDiceLoss(OpTestCase):
    op_type = "dice_loss_s"
    # input (N, C) probabilities; label (N, 1) int class id
    p = rng.uniform(0.1, 0.9, (10, 3)).astype(np.float32)
    p = p / p.sum(1, keepdims=True)
    label = rng.randint(0, 3, (10, 1)).astype(np.int64)

    inputs = {"X": p, "Label": label}
    attrs = {"epsilon": 1e-5}
    _oh = np.eye(3, dtype=np.float32)[label[:, 0]]
    inter = (p * _oh).sum(1)
    union = p.sum(1) + _oh.sum(1)
    dice = (2 * inter + 1e-5) / (union + 1e-5)
    outputs = {"Out": np.asarray((1 - dice).mean(), np.float32)}

    def test(self):
        self.check_output(atol=1e-5)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


class TestElu(OpTestCase):
    op_type = "elu_s"
    x = rng.randn(4, 6).astype(np.float32)
    inputs = {"X": x}
    attrs = {"alpha": 1.2}
    outputs = {"Out": np.where(x > 0, x, 1.2 * (np.exp(x) - 1)
                               ).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestBRelu(OpTestCase):
    op_type = "brelu_s"
    x = (rng.randn(4, 6) * 10).astype(np.float32)
    inputs = {"X": x}
    attrs = {"t_min": 1.0, "t_max": 8.0}
    outputs = {"Out": np.clip(x, 1.0, 8.0)}

    def test(self):
        self.check_output(atol=1e-6)


class TestHardSigmoid(OpTestCase):
    op_type = "hard_sigmoid_s"
    x = (rng.randn(5, 5) * 3).astype(np.float32)
    inputs = {"X": x}
    attrs = {"slope": 0.2, "offset": 0.5}
    outputs = {"Out": np.clip(0.2 * x + 0.5, 0, 1).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-6)


class TestMish(OpTestCase):
    op_type = "mish_s"
    x = rng.randn(4, 4).astype(np.float32)
    inputs = {"X": x}
    outputs = {"Out": (x * np.tanh(np.log1p(np.exp(x)))
                       ).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestMaxout(OpTestCase):
    op_type = "maxout_s"
    x = rng.randn(2, 6, 3, 3).astype(np.float32)   # C=6, groups=2 -> 3
    inputs = {"X": x}
    attrs = {"groups": 2}
    outputs = {"Out": x.reshape(2, 3, 2, 3, 3).max(axis=2)}

    def test(self):
        self.check_output(atol=1e-6)


# ---------------------------------------------------------------------------
# misc math / vision
# ---------------------------------------------------------------------------


class TestAffineChannel(OpTestCase):
    op_type = "affine_channel_s"
    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    scale = rng.randn(3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    inputs = {"X": x, "Scale": scale, "Bias": bias}
    outputs = {"Out": x * scale[None, :, None, None] +
               bias[None, :, None, None]}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestL2Normalize(OpTestCase):
    op_type = "l2_normalize_s"
    x = rng.randn(4, 8).astype(np.float32)
    inputs = {"X": x}
    attrs = {"axis": 1, "epsilon": 1e-12}
    outputs = {"Out": x / np.sqrt((x * x).sum(1, keepdims=True) + 1e-12)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"])


class TestIouSimilarity(OpTestCase):
    op_type = "iou_similarity_s"
    a = np.asarray([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
    b = np.asarray([[0, 0, 10, 10], [100, 100, 110, 110]], np.float32)
    inputs = {"X": a, "Y": b}

    @staticmethod
    def _iou(a, b):
        out = np.zeros((len(a), len(b)), np.float32)
        for i, p in enumerate(a):
            for j, q in enumerate(b):
                ix1, iy1 = max(p[0], q[0]), max(p[1], q[1])
                ix2, iy2 = min(p[2], q[2]), min(p[3], q[3])
                iw, ih = max(0, ix2 - ix1), max(0, iy2 - iy1)
                inter = iw * ih
                ua = ((p[2] - p[0]) * (p[3] - p[1]) +
                      (q[2] - q[0]) * (q[3] - q[1]) - inter)
                out[i, j] = inter / ua if ua > 0 else 0
        return out

    outputs = {"Out": _iou.__func__(a, b)}

    def test(self):
        self.check_output(atol=1e-5)


class TestFsp(OpTestCase):
    op_type = "fsp_s"
    a = rng.randn(2, 3, 4, 4).astype(np.float32)
    b = rng.randn(2, 5, 4, 4).astype(np.float32)
    inputs = {"X": a, "Y": b}
    outputs = {"Out": np.einsum("nchw,ndhw->ncd", a, b) / 16.0}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"], max_relative_error=0.06)


class TestBoxClip(OpTestCase):
    op_type = "box_clip_s"
    boxes = np.asarray([[[-5, -5, 20, 20], [2, 2, 8, 8]]], np.float32)
    im_info = np.asarray([[10, 12, 1.0]], np.float32)
    inputs = {"Input": boxes, "ImInfo": im_info}
    # clip to [0, w-1] x [0, h-1]
    outputs = {"Out": np.asarray([[[0, 0, 11, 9], [2, 2, 8, 8]]],
                                 np.float32)}

    def test(self):
        self.check_output(atol=1e-5)


class TestClipByNorm(OpTestCase):
    op_type = "clip_by_norm_s"
    x = (rng.randn(6) * 5).astype(np.float32)
    inputs = {"X": x}
    attrs = {"max_norm": 2.0}
    n = np.sqrt((x * x).sum())
    outputs = {"Out": (x * 2.0 / n if n > 2.0 else x).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-5)


class TestAddPositionEncoding(OpTestCase):
    op_type = "add_position_encoding_s"
    x = rng.randn(2, 4, 6).astype(np.float32)
    inputs = {"X": x}
    attrs = {"alpha": 1.0, "beta": 1.0}

    @staticmethod
    def _pe(x):
        b, t, d = x.shape
        half = d // 2
        pos = np.arange(t, dtype=np.float32)[:, None]
        denom = half - 1 if half > 1 else 1
        div = np.exp(np.arange(half, dtype=np.float32) *
                     -(np.log(10000.0) / denom))
        enc = np.concatenate([np.sin(pos * div), np.cos(pos * div)], 1)
        return x + enc[None]

    outputs = {"Out": _pe.__func__(x)}

    def test(self):
        self.check_output(atol=1e-4)
