"""Two-process collective execution (reference
unittests/test_collective_base.py:144-189 check_with_place: Popen two
ranks with env wiring, compare outputs). Proves the jax.distributed
coordination path end-to-end on CPU: init, cross-process allgather, and
a jitted DP step whose global-mean loss matches a single-process
full-batch run exactly."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_collective_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _reference_losses(nproc=2, per=4, steps=3):
    rng = np.random.RandomState(0)
    X = rng.randn(per * nproc, 4).astype(np.float32)
    Y = rng.randn(per * nproc, 1).astype(np.float32)
    W = rng.randn(4, 1).astype(np.float32)
    out = []
    for _ in range(steps):
        pred = X @ W
        out.append(float(np.mean((pred - Y) ** 2)))
        grad = 2.0 * X.T @ (pred - Y) / len(X)
        W = W - 0.1 * grad
    return out


@pytest.mark.slow
def test_two_process_allreduce_and_dp_step():
    nproc = 2
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if not k.startswith(("JAX_", "XLA_"))}
    procs = []
    for rank in range(nproc):
        env = dict(env_base,
                   PADDLE_TRAINER_ID=str(rank),
                   PADDLE_TRAINERS_NUM=str(nproc),
                   PADDLE_COORDINATOR=f"127.0.0.1:{port}")
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"ALLGATHER {rank} OK" in out, out
        assert f"DONE {rank}" in out, out

    # loss parity: every rank's global-mean loss at every step must equal
    # the single-process full-batch value (the reference's check_with_place
    # loss-delta criterion, exact here because the math is identical)
    ref = _reference_losses(nproc)
    for rank, out in enumerate(outs):
        losses = [float(line.split()[3]) for line in out.splitlines()
                  if line.startswith(f"LOSS {rank} ")]
        assert len(losses) == len(ref), out
        np.testing.assert_allclose(losses, ref, rtol=1e-5)


_WORKER_2X2 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_collective_worker_2x2.py")


def _reference_losses_2x2(steps=3):
    rng = np.random.RandomState(0)
    per, dp = 4, 2
    X = rng.randn(per * dp, 4).astype(np.float32)
    Y = rng.randn(per * dp, 1).astype(np.float32)
    W1 = rng.randn(4, 8).astype(np.float32) * 0.5
    W2 = rng.randn(8, 1).astype(np.float32) * 0.5
    out = []
    for _ in range(steps):
        H = np.maximum(X @ W1, 0.0)
        pred = H @ W2
        out.append(float(np.mean((pred - Y) ** 2)))
        d = 2.0 * (pred - Y) / len(X)             # dL/dpred
        g2 = H.T @ d
        dh = (d @ W2.T) * (H > 0)
        g1 = X.T @ dh
        W1, W2 = W1 - 0.1 * g1, W2 - 0.1 * g2
    return out


@pytest.mark.slow
def test_four_process_2x2_mesh_via_launch(tmp_path):
    """VERDICT r2 item 8: 4 subprocesses forming a dp2 x tp2 mesh over
    jax.distributed, launched END-TO-END through
    distributed/launch.py's start_local_trainers +
    watch_local_trainers (fleet/launch_utils.py:351/:418 path), with
    per-step loss parity vs a single-process numpy reference."""
    from paddle_tpu.distributed.launch import (start_local_trainers,
                                               watch_local_trainers)

    saved = dict(os.environ)
    try:
        # the launcher copies os.environ into each worker; give workers
        # a clean jax slate + the output dir (workers also self-scrub)
        os.environ.pop("JAX_PLATFORMS", None)
        os.environ["PADDLE_TEST_OUT"] = str(tmp_path)
        procs = start_local_trainers(4, [_WORKER_2X2],
                                     base_port=_free_port())
        rc = watch_local_trainers(procs)
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0

    ref = _reference_losses_2x2()
    for rank in range(4):
        with open(tmp_path / f"losses_rank{rank}.json") as f:
            losses = json.load(f)
        np.testing.assert_allclose(losses, ref, rtol=1e-5,
                                   err_msg=f"rank {rank}")
