"""Two-process collective execution (reference
unittests/test_collective_base.py:144-189 check_with_place: Popen two
ranks with env wiring, compare outputs). Proves the jax.distributed
coordination path end-to-end on CPU: init, cross-process allgather, and
a jitted DP step whose global-mean loss matches a single-process
full-batch run exactly."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_collective_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _reference_losses(nproc=2, per=4, steps=3):
    rng = np.random.RandomState(0)
    X = rng.randn(per * nproc, 4).astype(np.float32)
    Y = rng.randn(per * nproc, 1).astype(np.float32)
    W = rng.randn(4, 1).astype(np.float32)
    out = []
    for _ in range(steps):
        pred = X @ W
        out.append(float(np.mean((pred - Y) ** 2)))
        grad = 2.0 * X.T @ (pred - Y) / len(X)
        W = W - 0.1 * grad
    return out


@pytest.mark.slow
def test_two_process_allreduce_and_dp_step():
    nproc = 2
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if not k.startswith(("JAX_", "XLA_"))}
    procs = []
    for rank in range(nproc):
        env = dict(env_base,
                   PADDLE_TRAINER_ID=str(rank),
                   PADDLE_TRAINERS_NUM=str(nproc),
                   PADDLE_COORDINATOR=f"127.0.0.1:{port}")
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"ALLGATHER {rank} OK" in out, out
        assert f"DONE {rank}" in out, out

    # loss parity: every rank's global-mean loss at every step must equal
    # the single-process full-batch value (the reference's check_with_place
    # loss-delta criterion, exact here because the math is identical)
    ref = _reference_losses(nproc)
    for rank, out in enumerate(outs):
        losses = [float(line.split()[3]) for line in out.splitlines()
                  if line.startswith(f"LOSS {rank} ")]
        assert len(losses) == len(ref), out
        np.testing.assert_allclose(losses, ref, rtol=1e-5)
