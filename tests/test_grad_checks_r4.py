"""Numeric gradient checks (reference OpTest check_grad, SURVEY §4.1)
for the round-4 kernels: the fluid.layers activation tail
(softshrink/hard_shrink/thresholded_relu/tanh_shrink/logsigmoid/erf,
cumsum variants) and the new functional bilinear/cosine_similarity.
Central differences vs jax.grad; inputs avoid the kink points of the
piecewise ops so the finite-difference is well-defined."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.static.kernels import KERNELS

pytestmark = pytest.mark.slow


from tests.op_test import check_grad as _check  # shared harness


def _k(op, x, **attrs):
    out = KERNELS[op]({"X": [x]}, attrs, None)
    return out["Out"][0] if isinstance(out, dict) else out[0]


# kink-free inputs per op: piecewise ops get values away from their
# thresholds (|x| near 0.5 / 1.0 would break central differences)
CASES = [
    ("softshrink", np.array([-2.0, -1.2, 0.1, 0.2, 1.4, 2.5]),
     {"lambda": 0.5}),
    ("hard_shrink", np.array([-2.0, -1.2, 0.1, 0.2, 1.4, 2.5]),
     {"threshold": 0.5}),
    ("thresholded_relu", np.array([-2.0, 0.3, 0.7, 1.6, 2.5]),
     {"threshold": 1.0}),
    ("tanh_shrink", np.array([-1.5, -0.3, 0.2, 0.8, 2.0]), {}),
    ("logsigmoid", np.array([-2.0, -0.5, 0.0, 1.0, 3.0]), {}),
    ("erf", np.array([-1.5, -0.5, 0.0, 0.7, 1.8]), {}),
    ("cumsum", np.array([0.5, -1.0, 2.0, 0.3]), {"axis": 0}),
    ("cumsum", np.array([0.5, -1.0, 2.0, 0.3]),
     {"axis": 0, "reverse": True}),
    ("cumsum", np.array([0.5, -1.0, 2.0, 0.3]),
     {"axis": 0, "exclusive": True}),
]


@pytest.mark.parametrize("op,x,attrs", CASES,
                         ids=[f"{c[0]}-{i}" for i, c in enumerate(CASES)])
def test_kernel_gradient(op, x, attrs):
    _check(lambda v: jnp.sum(jnp.sin(_k(op, v, **attrs))), x)


def test_bilinear_gradient():
    from paddle_tpu.nn.functional import bilinear

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(3, 2, 4).astype(np.float32))
    x2 = jnp.asarray(rng.randn(2, 4).astype(np.float32))

    def f(x1):
        return jnp.sum(bilinear.raw_fn(x1, x2, w))

    _check(f, rng.randn(2, 2).astype(np.float32))


def test_cosine_similarity_gradient():
    from paddle_tpu.nn.functional import cosine_similarity

    rng = np.random.RandomState(1)
    b = jnp.asarray(rng.randn(3, 5).astype(np.float32))

    def f(a):
        return jnp.sum(cosine_similarity.raw_fn(a, b, axis=1))

    _check(f, rng.randn(3, 5).astype(np.float32))
