"""Round-3 third export sweep: roi-pool variants, CTR/focus ops,
LoD/SelectedRows bridge ops, py_reader family (vs numpy
transliterations of psroi_pool_op.h, prroi_pool_op.h,
deformable_psroi_pooling_op.h, cvm_op.h, filter_by_instag_op.h,
similarity_focus_op.cc, lod_reset/lod_append, merge_selected_rows,
create_py_reader_op)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.framework.errors import EOFException
from paddle_tpu.framework.lod import LoDTensor
from paddle_tpu.vision import ops as vops

L = static.layers


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


# ---------------------------------------------------------------------------
# psroi / prroi / deformable roi pooling
# ---------------------------------------------------------------------------


def test_psroi_pool_vs_loop():
    rng = np.random.RandomState(0)
    oc, ph, pw = 2, 2, 2
    x = rng.randn(1, oc * ph * pw, 8, 8).astype(np.float32)
    rois = np.asarray([[0, 0, 3, 3], [2, 2, 7, 7]], np.float32)
    out = _np(vops.psroi_pool(x, rois, oc, 1.0, ph, pw,
                              rois_lengths=np.asarray([2])))
    assert out.shape == (2, oc, ph, pw)
    # numpy reference for roi 0, channel 0, bin (0, 0)
    sw, sh = round(0) * 1.0, round(0) * 1.0
    ew, eh = (round(3) + 1.0), (round(3) + 1.0)
    bh, bw = max(eh - sh, 0.1) / ph, max(ew - sw, 0.1) / pw
    hs, he = int(np.floor(0 * bh + sh)), int(np.ceil(1 * bh + sh))
    ws, we = int(np.floor(0 * bw + sw)), int(np.ceil(1 * bw + sw))
    ch = (0 * ph + 0) * pw + 0
    expect = x[0, ch, hs:he, ws:we].sum() / ((he - hs) * (we - ws))
    np.testing.assert_allclose(out[0, 0, 0, 0], expect, rtol=1e-5)


def test_psroi_pool_channel_mismatch_raises():
    with pytest.raises(ValueError):
        vops.psroi_pool(np.zeros((1, 7, 4, 4), np.float32),
                        np.zeros((1, 4), np.float32), 2, 1.0, 2, 2)


def test_prroi_pool_constant_field_is_exact():
    # over a constant feature map the precise integral equals the
    # constant regardless of roi alignment — the op's defining property
    x = np.full((1, 3, 10, 10), 2.5, np.float32)
    rois = np.asarray([[1.3, 2.7, 6.1, 8.9]], np.float32)
    out = _np(vops.prroi_pool(x, rois, 1.0, 2, 2,
                              batch_roi_nums=np.asarray([1])))
    assert out.shape == (1, 3, 2, 2)
    # interior bins fully covered by the constant field
    np.testing.assert_allclose(out, 2.5, rtol=1e-4)


def test_prroi_pool_matches_triangle_integral_1d():
    # ramp image: integral of bilinear surface over bin == analytic mean
    h = w = 8
    x = np.broadcast_to(np.arange(w, dtype=np.float32), (h, w)).copy()
    x = x[None, None]
    rois = np.asarray([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = _np(vops.prroi_pool(x, rois, 1.0, 1, 1))
    # over [1, 5]^2 the ramp f(x)=x has mean 3.0
    np.testing.assert_allclose(out.reshape(()), 3.0, rtol=1e-5)


def test_deformable_roi_pooling_no_trans_matches_avg():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.asarray([[0, 0, 7, 7]], np.float32)
    trans = np.zeros((1, 2, 1, 1), np.float32)
    out = _np(vops.deformable_roi_pooling(
        x, rois, trans, no_trans=True, spatial_scale=1.0,
        group_size=(1, 1), pooled_height=2, pooled_width=2,
        sample_per_part=4))
    assert out.shape == (1, 2, 2, 2)
    assert np.isfinite(out).all()
    # zero offsets + dense sampling ~ bin average of the bilinear field
    approx = x[0, 0, 0:4, 0:4].mean()
    assert abs(out[0, 0, 0, 0] - approx) < 0.5


def test_deformable_roi_pooling_offsets_shift_window():
    # constant-gradient image: a positive x-offset increases the pooled
    # value by offset * gradient
    h = w = 16
    img = np.broadcast_to(np.arange(w, dtype=np.float32), (h, w)).copy()
    x = img[None, None]
    rois = np.asarray([[2, 2, 9, 9]], np.float32)
    z = np.zeros((1, 2, 1, 1), np.float32)
    t = np.zeros((1, 2, 1, 1), np.float32)
    t[0, 0] = 1.0   # x-offset, scaled by trans_std * roi_width
    base = _np(vops.deformable_roi_pooling(
        x, rois, z, pooled_height=1, pooled_width=1, sample_per_part=4,
        trans_std=0.1))
    shifted = _np(vops.deformable_roi_pooling(
        x, rois, t, pooled_height=1, pooled_width=1, sample_per_part=4,
        trans_std=0.1))
    assert shifted[0, 0, 0, 0] > base[0, 0, 0, 0]


# ---------------------------------------------------------------------------
# cvm / filter_by_instag / similarity_focus
# ---------------------------------------------------------------------------


def test_continuous_value_model():
    x = np.asarray([[3.0, 1.0, 7.0, 8.0]], np.float32)
    out = _np(L.continuous_value_model(x, None, use_cvm=True))
    np.testing.assert_allclose(
        out[0, :2], [np.log(4.0), np.log(2.0) - np.log(4.0)], rtol=1e-5)
    np.testing.assert_allclose(out[0, 2:], [7.0, 8.0])
    out2 = _np(L.continuous_value_model(x, None, use_cvm=False))
    np.testing.assert_allclose(out2, [[7.0, 8.0]])


def test_filter_by_instag():
    ins = np.arange(12, dtype=np.float32).reshape(4, 3)
    tags = np.asarray([[1, -1], [2, 3], [4, -1], [3, 5]], np.int64)
    out, lw, imap = L.filter_by_instag(ins, tags, np.asarray([3]))
    np.testing.assert_allclose(_np(out), ins[[1, 3]])
    np.testing.assert_allclose(_np(lw), [[1.0], [1.0]])
    np.testing.assert_array_equal(_np(imap)[:, 1], [1, 3])
    # empty match -> guard row
    out2, lw2, _ = L.filter_by_instag(ins, tags, np.asarray([99]),
                                      out_val_if_empty=7)
    assert _np(out2).shape == (1, 3)
    assert (_np(out2) == 7).all() and float(_np(lw2)[0, 0]) == 0.0


def test_similarity_focus_unique_rows_cols():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 4, 5).astype(np.float32)
    out = _np(L.similarity_focus(x, axis=1, indexes=[0, 2]))
    assert out.shape == x.shape
    # mask broadcast identically over the axis
    np.testing.assert_allclose(out[:, 0], out[:, 1])
    # per batch: the merged mask of one index has min(B,C)=4 picks with
    # unique rows/cols; union of 2 indexes is between 4 and 8
    per_image = out[:, 0].reshape(2, -1).sum(1)
    assert ((per_image >= 4) & (per_image <= 8)).all()


# ---------------------------------------------------------------------------
# LoD / SelectedRows bridges
# ---------------------------------------------------------------------------


def test_lod_reset_append_and_rank_reorder():
    data = np.arange(10, dtype=np.float32).reshape(5, 2)
    t = LoDTensor(data, [[0, 2, 5]])
    t2 = L.lod_reset(t, target_lod=[0, 1, 5])
    assert t2.lod()[0] == [0, 1, 5]
    t3 = L.lod_append(t2, [0, 1, 2, 3, 4, 5])
    assert len(t3.lod()) == 2
    # rank by length desc: seq1 (len 4) before seq0 (len 1)
    table = L.lod_rank_table(t2)
    assert [i for i, _ in table.items] == [1, 0]
    r = L.reorder_lod_tensor_by_rank(t2, table)
    np.testing.assert_allclose(np.asarray(r.data)[:4], data[1:5])
    assert r.recursive_sequence_lengths()[0] == [4, 1]


def test_selected_rows_merge_and_densify():
    sr = L.SelectedRows([3, 1, 3], np.asarray(
        [[1.0, 1.0], [2.0, 2.0], [10.0, 10.0]], np.float32), height=5)
    m = L.merge_selected_rows(sr)
    np.testing.assert_array_equal(m.rows, [1, 3])
    np.testing.assert_allclose(m.value, [[2, 2], [11, 11]])
    dense = _np(L.get_tensor_from_selected_rows(m))
    assert dense.shape == (5, 2)
    np.testing.assert_allclose(dense[3], [11, 11])
    np.testing.assert_allclose(dense[0], [0, 0])


# ---------------------------------------------------------------------------
# py_reader family
# ---------------------------------------------------------------------------


def test_py_reader_feeds_executor_until_eof():
    prog = static.Program()
    with static.program_guard(prog):
        reader = L.py_reader(capacity=4, shapes=[[-1, 3], [-1, 1]],
                             dtypes=["float32", "int64"])
        x, y = L.read_file(reader)
        out = L.elementwise_add(x, L.cast(y, "float32"))

    batches = [(np.ones((2, 3), np.float32) * i,
                np.full((2, 1), i, np.int64)) for i in range(3)]
    reader.decorate_batch_generator(lambda: iter(batches))
    reader.start()
    exe = static.Executor()
    seen = 0
    while True:
        try:
            (o,) = exe.run(prog, fetch_list=[out])
        except EOFException:
            reader.reset()
            break
        np.testing.assert_allclose(np.asarray(o),
                                   np.full((2, 3), 2 * seen, np.float32))
        seen += 1
    assert seen == 3
    # restartable after reset
    reader.start()
    (o,) = exe.run(prog, fetch_list=[out])
    assert np.asarray(o).shape == (2, 3)
    reader.reset()


def test_double_buffer_identity_and_by_data():
    prog = static.Program()
    with static.program_guard(prog):
        x = L.data(name="pr_x", shape=[2, 2], dtype="float32")
        reader = L.create_py_reader_by_data(4, [x])
        assert L.double_buffer(reader) is reader
        assert L.read_file(reader) is x


def test_py_reader_sample_list_generator():
    """paddle.batch format: a LIST of per-sample tuples per batch gets
    stacked into per-slot arrays (decorate_sample_list_generator)."""
    prog = static.Program()
    with static.program_guard(prog):
        reader = L.py_reader(capacity=2, shapes=[[-1, 2], [-1, 1]],
                             dtypes=["float32", "int64"])
        x, y = L.read_file(reader)
        out = L.elementwise_add(x, L.cast(y, "float32"))

    def batches():
        yield [(np.ones(2, np.float32), np.asarray([1]))
               for _ in range(4)]

    reader.decorate_sample_list_generator(batches)
    reader.start()
    exe = static.Executor()
    (o,) = exe.run(prog, fetch_list=[out])
    assert np.asarray(o).shape == (4, 2)
    np.testing.assert_allclose(np.asarray(o), 2.0)
    reader.reset()
