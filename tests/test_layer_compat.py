"""Tests for the fluid.layers long-tail compatibility batch (the ops the
coverage audit against the reference layers' __all__ found missing)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

pytestmark = pytest.mark.slow


def t(a):
    return paddle.to_tensor(np.asarray(a))


# -- math/manipulation ------------------------------------------------------
def test_multiplex():
    a = np.arange(6, dtype=np.float32).reshape(3, 2)
    b = a + 10
    out = paddle.multiplex([t(a), t(b)], t(np.array([0, 1, 0])))
    np.testing.assert_array_equal(out.numpy(), [[0, 1], [12, 13], [4, 5]])


def test_has_inf_nan():
    assert bool(paddle.has_inf(t([1.0, np.inf])).numpy())
    assert not bool(paddle.has_inf(t([1.0, 2.0])).numpy())
    assert bool(paddle.has_nan(t([np.nan])).numpy())


def test_clip_by_norm():
    x = np.array([3.0, 4.0], np.float32)   # norm 5
    out = paddle.clip_by_norm(t(x), 1.0).numpy()
    np.testing.assert_allclose(np.linalg.norm(out), 1.0, rtol=1e-5)
    same = paddle.clip_by_norm(t(x), 10.0).numpy()
    np.testing.assert_allclose(same, x, rtol=1e-6)


def test_cos_sim():
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    got = paddle.cos_sim(t(x), t(y)).numpy().ravel()
    expect = (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                * np.linalg.norm(y, axis=-1))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_hash_deterministic_in_range():
    ids = np.array([[1, 2], [3, 4]], np.int64)
    h1 = paddle.hash_(t(ids), num_hash=2, mod_by=1000).numpy()
    h2 = paddle.hash_(t(ids), num_hash=2, mod_by=1000).numpy()
    np.testing.assert_array_equal(h1, h2)
    assert h1.shape == (2, 2, 2)
    assert h1.min() >= 0 and h1.max() < 1000
    assert len(np.unique(h1)) > 1


def test_add_position_encoding():
    x = np.zeros((1, 4, 8), np.float32)
    out = paddle.add_position_encoding(t(x), alpha=1.0, beta=1.0).numpy()
    # position 0: sin(0)=0, cos(0)=1 halves
    np.testing.assert_allclose(out[0, 0, :4], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 4:], 1.0, atol=1e-6)


def test_reverse_shape_size_rank():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_array_equal(paddle.reverse(t(x), 1).numpy(),
                                  x[:, ::-1])
    np.testing.assert_array_equal(paddle.shape(t(x)).numpy(), [2, 3])
    assert int(paddle.size(t(x)).numpy()) == 6
    assert int(paddle.rank(t(x)).numpy()) == 2


def test_space_to_depth_shuffle_channel():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = paddle.space_to_depth(t(x), 2).numpy()
    assert out.shape == (1, 4, 2, 2)
    np.testing.assert_array_equal(out[0, 0], [[0, 2], [8, 10]])
    c = np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1)
    sh = paddle.shuffle_channel(t(c), 2).numpy().ravel()
    np.testing.assert_array_equal(sh, [0, 4, 1, 5, 2, 6, 3, 7])


def test_pad_constant_like_crop_fill_like():
    x = np.zeros((3, 4), np.float32)
    y = np.ones((2, 2), np.float32)
    out = paddle.pad_constant_like(t(x), t(y), 5.0).numpy()
    assert out.shape == (3, 4) and out[0, 0] == 1 and out[2, 3] == 5
    crop = paddle.crop_tensor(t(out), shape=[2, 2], offsets=[1, 1]).numpy()
    assert crop.shape == (2, 2)
    f = paddle.fill_constant_batch_size_like(t(x), [-1, 7], "float32", 3.0)
    assert tuple(f.shape) == (3, 7) and float(f.numpy()[0, 0]) == 3.0


def test_unique_with_counts():
    out, idx, cnt = paddle.unique_with_counts(t(np.array([2, 1, 2, 3])))
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3])
    np.testing.assert_array_equal(cnt.numpy(), [1, 2, 1])
    np.testing.assert_array_equal(out.numpy()[idx.numpy()], [2, 1, 2, 3])


# -- losses / activations ---------------------------------------------------
def test_brelu_soft_relu():
    x = np.array([-1.0, 5.0, 30.0], np.float32)
    np.testing.assert_array_equal(F.brelu(t(x), 0.0, 24.0).numpy(),
                                  [0, 5, 24])
    np.testing.assert_allclose(F.soft_relu(t(x)).numpy(),
                               np.log1p(np.exp(np.clip(x, -40, 40))),
                               rtol=1e-5)


def test_dice_loss_perfect_prediction():
    label = np.array([[0], [1], [2]], np.int64)
    probs = np.eye(3, dtype=np.float32)
    loss = float(F.dice_loss(t(probs), t(label)).numpy())
    assert loss < 1e-3


def test_rank_and_margin_rank_loss():
    label = np.array([[1.0]], np.float32)
    left = np.array([[2.0]], np.float32)
    right = np.array([[1.0]], np.float32)
    rl = float(F.rank_loss(t(label), t(left), t(right)).numpy())
    np.testing.assert_allclose(rl, -1.0 + np.log1p(np.exp(1.0)), rtol=1e-5)
    m = F.margin_rank_loss(t(label), t(left), t(right), margin=0.5).numpy()
    np.testing.assert_allclose(m, 0.0)


def test_bpr_loss_prefers_correct_class():
    good = np.array([[5.0, 0.0, 0.0]], np.float32)
    bad = np.array([[0.0, 5.0, 5.0]], np.float32)
    lbl = np.array([[0]], np.int64)
    assert float(F.bpr_loss(t(good), t(lbl)).numpy()) < \
        float(F.bpr_loss(t(bad), t(lbl)).numpy())


def test_center_loss_zero_at_center():
    centers = np.array([[1.0, 1.0], [2.0, 2.0]], np.float32)
    x = np.array([[1.0, 1.0]], np.float32)
    loss = F.center_loss(t(x), t(np.array([0])), t(centers)).numpy()
    np.testing.assert_allclose(loss, 0.0)


def test_bilinear_tensor_product():
    x = np.array([[1.0, 2.0]], np.float32)
    y = np.array([[3.0, 4.0]], np.float32)
    w = np.zeros((2, 2, 2), np.float32)
    w[0] = np.eye(2)
    out = F.bilinear_tensor_product_fn(t(x), t(y), t(w)).numpy()
    np.testing.assert_allclose(out, [[11.0, 0.0]], rtol=1e-6)


def test_affine_channel():
    x = np.ones((1, 2, 2, 2), np.float32)
    out = F.affine_channel(t(x), t(np.array([2.0, 3.0])),
                           t(np.array([1.0, 0.0]))).numpy()
    assert out[0, 0, 0, 0] == 3.0 and out[0, 1, 0, 0] == 3.0


def test_row_conv():
    x = np.ones((1, 4, 2), np.float32)
    w = np.ones((2, 2), np.float32)
    out = F.row_conv(t(x), t(w)).numpy()
    # last step sees only itself (future padded)
    np.testing.assert_allclose(out[0, -1], 1.0)
    np.testing.assert_allclose(out[0, 0], 2.0)


# -- vision extras ----------------------------------------------------------
def test_mean_iou():
    from paddle_tpu.vision.ops import mean_iou

    pred = np.array([0, 1, 1, 0])
    gt = np.array([0, 1, 0, 0])
    miou, wrong, correct = mean_iou(t(pred), t(gt), 2)
    np.testing.assert_allclose(float(miou.numpy()),
                               ((2 / 3) + (1 / 2)) / 2, rtol=1e-5)


def test_box_clip_and_bipartite_match():
    from paddle_tpu.vision.ops import bipartite_match, box_clip

    boxes = np.array([[-5.0, -5.0, 20.0, 30.0]], np.float32)
    im_info = np.array([10.0, 10.0, 1.0], np.float32)
    out = box_clip(t(boxes), t(im_info)).numpy()
    np.testing.assert_allclose(out, [[0, 0, 9, 9]])

    dist = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
    idx, d = bipartite_match(t(dist))
    np.testing.assert_array_equal(idx.numpy(), [[0, 1]])
    np.testing.assert_allclose(d.numpy(), [[0.9, 0.8]], rtol=1e-6)


def test_roi_pool():
    from paddle_tpu.vision.ops import roi_pool

    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    out = roi_pool(t(x), t(rois), 2).numpy()
    assert out.shape == (1, 1, 2, 2)
    assert out[0, 0, 1, 1] == 15.0  # max of bottom-right quadrant


# -- sequence extras --------------------------------------------------------
def test_sequence_concat_and_slice():
    from paddle_tpu.ops.sequence import sequence_concat, sequence_slice

    a = np.array([[1, 2, 0]], np.float32)[..., None]
    b = np.array([[7, 0, 0]], np.float32)[..., None]
    out, lens = sequence_concat([t(a), t(b)], [t([2]), t([1])])
    np.testing.assert_array_equal(lens.numpy(), [3])
    np.testing.assert_array_equal(out.numpy()[0, :3, 0], [1, 2, 7])

    x = np.arange(10, dtype=np.float32).reshape(1, 10)
    sl, ln = sequence_slice(t(x), t([10]), t([2]), t([3]))
    np.testing.assert_array_equal(sl.numpy()[0, :3], [2, 3, 4])
    assert int(ln.numpy()[0]) == 3


def test_sequence_enumerate_scatter():
    from paddle_tpu.ops.sequence import (sequence_enumerate,
                                         sequence_scatter)

    ids = np.array([[1, 2, 3, 0]], np.int64)
    out = sequence_enumerate(t(ids), t([3]), win_size=2, pad_value=0)
    np.testing.assert_array_equal(out.numpy()[0, 0], [1, 2])
    np.testing.assert_array_equal(out.numpy()[0, 2], [3, 0])

    x = np.zeros((1, 5), np.float32)
    got = sequence_scatter(t(x), t(np.array([[1, 3]])),
                           t(np.array([[2.0, 4.0]], np.float32)))
    np.testing.assert_array_equal(got.numpy(), [[0, 2, 0, 4, 0]])


# -- search/decode extras ---------------------------------------------------
def test_sampling_id_distribution():
    probs = np.tile(np.array([[0.0, 1.0, 0.0]], np.float32), (8, 1))
    ids = paddle.ops.search.sampling_id(t(probs), seed=3).numpy()
    np.testing.assert_array_equal(ids, np.ones(8))


def test_gather_tree():
    from paddle_tpu.ops.search import gather_tree

    ids = np.array([[[2, 5]], [[3, 6]], [[4, 7]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
    out = gather_tree(t(ids), t(parents)).numpy()
    # beam 0 at t=2 traces parent 0 at t=2 -> parent of that at t=1 is 1
    np.testing.assert_array_equal(out[:, 0, 0], [5, 3, 4])


def test_edit_distance():
    from paddle_tpu.ops.search import edit_distance

    hyp = np.array([[1, 2, 3]], np.int64)
    ref = np.array([[1, 3, 3]], np.int64)
    d, n = edit_distance(t(hyp), t(ref), normalized=False)
    assert float(d.numpy()[0, 0]) == 1.0 and int(n.numpy()) == 1


def test_ctc_greedy_decoder():
    from paddle_tpu.ops.search import ctc_greedy_decoder

    # classes: 0,1 + blank=2; frames argmax: [0,0,2,1,1,2,0] -> [0,1,0]
    T, C = 7, 3
    probs = np.zeros((1, T, C), np.float32)
    path = [0, 0, 2, 1, 1, 2, 0]
    for i, c in enumerate(path):
        probs[0, i, c] = 1.0
    ids, lens = ctc_greedy_decoder(t(probs), blank=2)
    assert int(lens.numpy()[0]) == 3
    np.testing.assert_array_equal(ids.numpy()[0, :3], [0, 1, 0])


# -- distributions ----------------------------------------------------------
def test_normal_distribution():
    from paddle_tpu.distribution import Normal

    n = Normal(0.0, 1.0)
    s = n.sample([2000], seed=7).numpy()
    assert abs(s.mean()) < 0.1 and abs(s.std() - 1.0) < 0.1
    lp = float(n.log_prob(t(0.0)).numpy())
    np.testing.assert_allclose(lp, -0.5 * np.log(2 * np.pi), rtol=1e-5)
    n2 = Normal(1.0, 2.0)
    kl = float(n.kl_divergence(n2).numpy())
    expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, expect, rtol=1e-5)


def test_uniform_and_categorical():
    from paddle_tpu.distribution import Categorical, Uniform

    u = Uniform(1.0, 3.0)
    s = u.sample([500], seed=5).numpy()
    assert s.min() >= 1.0 and s.max() < 3.0
    np.testing.assert_allclose(float(u.entropy().numpy()), np.log(2.0),
                               rtol=1e-6)
    c = Categorical(np.log(np.array([0.5, 0.5], np.float32)))
    np.testing.assert_allclose(float(c.entropy().numpy()), np.log(2.0),
                               rtol=1e-5)
    c2 = Categorical(np.log(np.array([0.9, 0.1], np.float32)))
    assert float(c.kl_divergence(c2).numpy()) > 0


def test_mvn_diag():
    from paddle_tpu.distribution import MultivariateNormalDiag

    m = MultivariateNormalDiag(np.zeros(2, np.float32),
                               np.ones(2, np.float32))
    lp = float(m.log_prob(t(np.zeros(2, np.float32))).numpy())
    np.testing.assert_allclose(lp, -np.log(2 * np.pi), rtol=1e-5)


# -- debug / host callbacks -------------------------------------------------
def test_print_passthrough(capfd):
    x = t(np.array([1.0, 2.0]))
    y = paddle.Print(x, message="dbg")
    np.testing.assert_array_equal(y.numpy(), [1.0, 2.0])


def test_assert_raises():
    paddle.Assert(t(np.array(True)))
    with pytest.raises(AssertionError):
        paddle.Assert(t(np.array(False)), data=[t(np.array([7]))])


def test_py_func_forward_and_backward():
    import jax

    def host(x):
        return x * 2.0

    def host_grad(x, g):
        return g * 2.0

    x = np.array([1.0, 2.0], np.float32)
    out = paddle.py_func(host, t(x), t(np.zeros(2, np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0, 4.0])

    # gradient path via custom_vjp under jax directly
    import jax.numpy as jnp

    def f(a):
        from paddle_tpu.framework.tensor import Tensor

        r = paddle.py_func(host, Tensor(a), t(np.zeros(2, np.float32)),
                           backward_func=host_grad)
        return jnp.sum(r.value)

    g = jax.grad(f)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), [2.0, 2.0])


# -- CRF --------------------------------------------------------------------
def _crf_brute_force(em, tr, lens):
    """Enumerate all paths for tiny cases."""
    import itertools

    start, stop, pair = tr[0], tr[1], tr[2:]
    B, L, T = em.shape
    logZ = np.zeros(B)
    best = []
    for b in range(B):
        n = int(lens[b])
        scores = {}
        for path in itertools.product(range(T), repeat=n):
            s = start[path[0]] + em[b, 0, path[0]] + stop[path[-1]]
            for i in range(1, n):
                s += pair[path[i - 1], path[i]] + em[b, i, path[i]]
            scores[path] = s
        vals = np.array(list(scores.values()))
        logZ[b] = np.log(np.exp(vals - vals.max()).sum()) + vals.max()
        best.append(max(scores, key=scores.get))
    return logZ, best


def test_linear_chain_crf_matches_brute_force():
    from paddle_tpu.nn.crf import crf_decoding, linear_chain_crf

    rng = np.random.RandomState(0)
    B, L, T = 3, 4, 3
    em = rng.randn(B, L, T).astype(np.float32)
    tr = rng.randn(T + 2, T).astype(np.float32)
    lens = np.array([4, 3, 1], np.int64)
    label = rng.randint(0, T, (B, L)).astype(np.int64)

    ll = linear_chain_crf(t(em), t(tr), t(label), t(lens)).numpy()[:, 0]
    logZ, best = _crf_brute_force(em, tr, lens)
    start, stop, pair = tr[0], tr[1], tr[2:]
    for b in range(B):
        n = int(lens[b])
        path = label[b, :n]
        s = start[path[0]] + em[b, 0, path[0]] + stop[path[-1]]
        for i in range(1, n):
            s += pair[path[i - 1], path[i]] + em[b, i, path[i]]
        np.testing.assert_allclose(ll[b], s - logZ[b], rtol=1e-4,
                                   atol=1e-5)

    decoded = crf_decoding(t(em), t(tr), t(lens)).numpy()
    for b in range(B):
        n = int(lens[b])
        np.testing.assert_array_equal(decoded[b, :n], best[b])
        assert np.all(decoded[b, n:] == 0)


def test_crf_layer_trains():
    from paddle_tpu import optimizer
    from paddle_tpu.nn.crf import LinearChainCRF

    paddle.seed(0)
    crf = nn.LinearChainCRF(num_tags=3)
    rng = np.random.RandomState(0)
    B, L = 8, 5
    em = rng.randn(B, L, 3).astype(np.float32)
    label = em.argmax(-1).astype(np.int64)  # learnable target
    lens = np.full(B, L, np.int64)
    opt = optimizer.Adam(learning_rate=0.1, parameters=crf.parameters())
    losses = []
    for _ in range(20):
        loss = crf(t(em), t(label), t(lens))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # decoding mask mode: agreement indicator
    mask = nn.crf_decoding(t(em), crf.transition, t(lens),
                           label=t(label)).numpy()
    assert mask.shape == (B, L)


def test_nce_and_sampled_softmax_train_signal():
    rng = np.random.RandomState(0)
    D, C, B = 8, 50, 16
    w = rng.randn(C, D).astype(np.float32) * 0.1
    x = w[:B] * 10  # inputs aligned with their own class vector
    label = np.arange(B).reshape(B, 1).astype(np.int64)
    # same pinned seed -> same negatives, so only the positive term
    # separates good from bad inputs
    good = F.nce(t(x), t(label), t(w), num_neg_samples=10, seed=3).numpy()
    bad = F.nce(t(-x), t(label), t(w), num_neg_samples=10, seed=3).numpy()
    assert good.mean() < bad.mean()
    # default draws fresh negatives each call
    a = F.nce(t(x), t(label), t(w), num_neg_samples=10).numpy()
    b = F.nce(t(x), t(label), t(w), num_neg_samples=10).numpy()
    assert not np.allclose(a, b)

    g2 = F.sampled_softmax_with_cross_entropy(
        t(w), t(x), t(label), num_samples=10, seed=3).numpy()
    b2 = F.sampled_softmax_with_cross_entropy(
        t(w), t(-x), t(label), num_samples=10, seed=3).numpy()
    assert g2.mean() < b2.mean()
    assert g2.shape == (B, 1)
