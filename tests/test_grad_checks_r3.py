"""Numeric gradient checks (the reference OpTest check_grad fixture,
SURVEY §4.1) for the round-3 differentiable ops: prroi_pool (exact
coordinate gradients are the op's defining property —
arXiv:1807.11590), deformable_roi_pooling (offset gradients),
bilinear_tensor_product, hsigmoid, row_conv, roi_perspective_transform.
Central differences vs jax.grad in f64-safe f32 with loose-but-real
tolerances."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401

# full-tensor central differences are deliberate and slow — slow tier
pytestmark = pytest.mark.slow


from tests.op_test import check_grad as _check  # shared harness


def test_prroi_pool_grad_wrt_input_and_rois():
    from paddle_tpu.vision.ops import prroi_pool

    rng = np.random.RandomState(0)
    img = rng.randn(1, 2, 6, 6).astype(np.float32)
    rois = np.asarray([[0.7, 1.2, 4.3, 4.9]], np.float32)

    def loss_img(x):
        out = prroi_pool(x, rois, 1.0, 2, 2)
        return (out.value ** 2).sum()

    _check(loss_img, img)

    # the PrRoI selling point: exact gradients wrt the roi COORDINATES
    def loss_rois(r):
        out = prroi_pool(img, r, 1.0, 2, 2)
        return (out.value ** 2).sum()

    _check(loss_rois, rois, rtol=0.08, atol=2e-2, delta=5e-3)


def test_deformable_roi_pooling_grad_wrt_offsets():
    from paddle_tpu.vision.ops import deformable_roi_pooling

    rng = np.random.RandomState(1)
    img = rng.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.asarray([[1, 1, 6, 6]], np.float32)
    trans = rng.randn(1, 2, 2, 2).astype(np.float32) * 0.1

    def loss(t):
        out = deformable_roi_pooling(
            img, rois, t, pooled_height=2, pooled_width=2,
            sample_per_part=2, trans_std=0.1)
        return (out.value ** 2).sum()

    # bilinear sampling is piecewise-smooth; keep the step small and
    # tolerate kinks at cell boundaries via atol
    _check(loss, trans, rtol=0.08, atol=3e-2, delta=2e-3)


def test_bilinear_tensor_product_grad():
    from paddle_tpu.nn.compat20 import bilinear

    rng = np.random.RandomState(2)
    x1 = rng.randn(3, 4).astype(np.float32)
    x2 = rng.randn(3, 5).astype(np.float32)
    w = rng.randn(2, 4, 5).astype(np.float32)

    def loss(wv):
        return (bilinear.raw_fn(jnp.asarray(x1), jnp.asarray(x2),
                                wv, None) ** 2).sum()

    _check(loss, w)


def test_hsigmoid_grad():
    from paddle_tpu.nn.compat20 import hsigmoid

    rng = np.random.RandomState(3)
    num_classes, dim, b = 6, 8, 4
    x = rng.randn(b, dim).astype(np.float32)
    w = rng.randn(num_classes - 1, dim).astype(np.float32)
    bias = rng.randn(num_classes - 1).astype(np.float32)
    label = rng.randint(0, num_classes, b)

    def loss_x(xv):
        return hsigmoid.raw_fn(xv, jnp.asarray(w), jnp.asarray(bias),
                               label, num_classes).sum()

    _check(loss_x, x)

    def loss_w(wv):
        return hsigmoid.raw_fn(jnp.asarray(x), wv, jnp.asarray(bias),
                               label, num_classes).sum()

    _check(loss_w, w)


def test_row_conv_grad():
    from paddle_tpu.nn.compat20 import _row_conv_fn

    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, 3).astype(np.float32)
    w = rng.randn(3, 3).astype(np.float32)

    def loss(wv):
        return (_row_conv_fn.raw_fn(jnp.asarray(x), wv) ** 2).sum()

    _check(loss, w)


def test_roi_perspective_transform_grad_wrt_input():
    from paddle_tpu.vision.ops import roi_perspective_transform

    rng = np.random.RandomState(5)
    img = rng.randn(1, 1, 8, 8).astype(np.float32)
    rois = np.asarray([[1, 1, 6, 1, 6, 6, 1, 6]], np.float32)

    def loss(x):
        out, _, _ = roi_perspective_transform(
            x, rois, transformed_height=4, transformed_width=4)
        return (out.value ** 2).sum()

    _check(loss, img, rtol=0.08, atol=2e-2)


def test_fused_embedding_bag_grad_matches_xla_path():
    from paddle_tpu.ops.pallas.fused_embedding import _bag_core, _xla_bag

    rng = np.random.RandomState(6)
    table = rng.randn(64, 128).astype(np.float32)
    ids = rng.randint(-1, 64, (8, 12)).astype(np.int32)

    def loss_custom(t):
        return (_bag_core(t, jnp.asarray(ids), "mean") ** 2).sum()

    def loss_ref(t):
        return (_xla_bag(t, jnp.asarray(ids), "mean") ** 2).sum()

    g1 = np.asarray(jax.grad(loss_custom)(jnp.asarray(table)))
    g2 = np.asarray(jax.grad(loss_ref)(jnp.asarray(table)))
    np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-5)
