"""Worker for the SIGTERM graceful-drain test (tests/test_serving.py):
builds a tiny inference blob, starts the continuous-batching engine,
queues a batch of requests, then SIGTERMs ITSELF. The
install_sigterm_drain handler must stop admission, flush every queued/
in-flight request, report how many completed, and exit 0 — the parent
asserts rc 0 and zero lost requests."""
import os
import signal
import sys
import tempfile
import time

import numpy as np


def main():
    import paddle_tpu.static as static
    from paddle_tpu.inference.serving import (AnalysisPredictor,
                                              ServingEngine,
                                              install_sigterm_drain)

    n_requests = int(os.environ.get("DRAIN_REQUESTS", "12"))
    with tempfile.TemporaryDirectory() as tmp:
        main_p, startup = static.Program(), static.Program()
        with static.program_guard(main_p, startup):
            x = static.data("x", [-1, 8])
            h = static.nn.fc(x, 16, act="relu")
            out = static.nn.fc(h, 3)
        exe = static.Executor()
        exe.run(startup)
        blob = os.path.join(tmp, "blob")
        static.save_inference_model(blob, ["x"], [out], exe, main_p)

        predictor = AnalysisPredictor(blob, batch_buckets=(1, 2, 4))
        predictor.warm()
        engine = ServingEngine(predictor).start()

        handles = [engine.submit(
            {"x": np.full((1 + i % 2, 8), float(i), np.float32)})
            for i in range(n_requests)]

        def report():
            # runs inside the SIGTERM handler AFTER engine.drain():
            # every admitted request must be resolved — served (value)
            # counts as kept; a typed failure would count as lost
            done = sum(1 for h in handles if h.done())
            ok = sum(1 for h in handles
                     if h.done() and h.error() is None)
            print(f"DRAINED done={done} ok={ok} total={n_requests}",
                  flush=True)

        install_sigterm_drain(engine, on_drained=report, exit_code=0)
        os.kill(os.getpid(), signal.SIGTERM)
        # unreachable when the handler exits; bounded fallback so a
        # broken handler fails the test by timeout-side assert, not hang
        time.sleep(30)
        print("HANDLER DID NOT EXIT", flush=True)
        sys.exit(3)


if __name__ == "__main__":
    main()
