"""Ragged paged attention (ops/pallas/paged_attention.py) in interpret
mode (CPU-hermetic): kernel parity against the XLA gather fallback and
a dense reference, page-write scatter semantics, dispatch counters,
the PADDLE_PAGED_ATTENTION=0 escape leg, and the autotune cache keys —
the same coverage contract the flash_attention kernel carries."""
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.framework.bringup as bringup
from paddle_tpu.ops.pallas import autotune, counters
from paddle_tpu.ops.pallas import paged_attention as pa


@pytest.fixture(autouse=True)
def interpret_pallas(monkeypatch):
    """Run pallas_call in interpret mode so kernels execute on CPU."""
    from jax.experimental import pallas as pl

    real = pl.pallas_call
    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(real, interpret=True))
    yield


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


def _pool(b=3, h=2, d=16, s=8, pages=12, t=3, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
    kp = jnp.asarray(rng.randn(pages, s, h, d), jnp.float32)
    vp = jnp.asarray(rng.randn(pages, s, h, d), jnp.float32)
    return q, kp, vp


def _dense_ref(q, kp, vp, table, lens):
    """Plain-softmax reference over the gathered pages."""
    B, H, D = q.shape
    S = kp.shape[1]
    T = table.shape[1]
    k = kp[jnp.maximum(table, 0)].reshape(B, T * S, H, D)
    v = vp[jnp.maximum(table, 0)].reshape(B, T * S, H, D)
    s = jnp.einsum("bhd,bkhd->bhk", q, k) / math.sqrt(D)
    pos = jnp.arange(T * S)
    s = jnp.where(pos[None, None, :] < lens[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, v)


def test_kernel_matches_fallback_and_dense_ragged():
    """Mixed lengths, partially filled tables, a part-filled tail
    page: the kernel, the XLA gather fallback, and the dense reference
    agree."""
    q, kp, vp = _pool()
    table = jnp.asarray([[1, 2, 3], [4, 5, -1], [6, -1, -1]], jnp.int32)
    lens = jnp.asarray([20, 11, 5], jnp.int32)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    out = pa._paged_attention_pallas(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dense = _dense_ref(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_kernel_single_token_and_full_table():
    q, kp, vp = _pool(b=2, t=4, pages=16, seed=3)
    table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    for lens in ([1, 32], [32, 1], [17, 9]):
        lens = jnp.asarray(lens, jnp.int32)
        ref = pa._xla_paged_attention(q, kp, vp, table, lens)
        out = pa._paged_attention_pallas(q, kp, vp, table, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_kernel_ignores_dead_page_contents():
    """Pages past ceil(len/S) and -1 table slots must not leak into the
    output whatever garbage they hold."""
    q, kp, vp = _pool(seed=5)
    table = jnp.asarray([[1, 2, -1], [3, -1, -1], [4, 5, 6]], jnp.int32)
    lens = jnp.asarray([10, 3, 24], jnp.int32)
    out1 = pa._paged_attention_pallas(q, kp, vp, table, lens)
    # poison every page the tables don't reach live
    kp2 = kp.at[7:].set(1e4).at[0].set(-1e4)
    vp2 = vp.at[7:].set(1e4).at[0].set(-1e4)
    out2 = pa._paged_attention_pallas(q, kp2, vp2, table, lens)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-6, atol=1e-6)


def test_paged_write_scatter_and_trash_page():
    """paged_write lands each sequence's token at page_table[pos//S],
    offset pos%S; inactive lanes land on the reserved page 0."""
    _, kp, vp = _pool(b=2, pages=6, t=2)
    table = jnp.asarray([[3, 4], [5, -1]], jnp.int32)
    positions = jnp.asarray([9, 2], jnp.int32)   # page 4 off 1, page 5 off 2
    new_k = jnp.full((2, 2, 16), 7.0, jnp.float32)
    new_v = jnp.full((2, 2, 16), -7.0, jnp.float32)
    k2, v2 = pa.paged_write(kp, vp, table, positions, new_k, new_v,
                            jnp.asarray([True, True]))
    np.testing.assert_allclose(np.asarray(k2[4, 1]), 7.0)
    np.testing.assert_allclose(np.asarray(v2[5, 2]), -7.0)
    # untouched elsewhere
    np.testing.assert_allclose(np.asarray(k2[3]), np.asarray(kp[3]))
    # inactive lane routes at the trash page 0 and clobbers nothing live
    k3, _ = pa.paged_write(kp, vp, table, positions, new_k, new_v,
                           jnp.asarray([False, False]))
    np.testing.assert_allclose(np.asarray(k3[1:]), np.asarray(kp[1:]))


def test_paged_prefill_write_roundtrip():
    _, kp, vp = _pool(pages=8)
    page_ids = jnp.asarray([2, 5], jnp.int32)
    new_k = jnp.arange(2 * 8 * 2 * 16, dtype=jnp.float32
                       ).reshape(16, 2, 16)
    k2, _ = pa.paged_prefill_write(kp, vp, page_ids, new_k, new_k)
    np.testing.assert_allclose(np.asarray(k2[2]),
                               np.asarray(new_k[:8]))
    np.testing.assert_allclose(np.asarray(k2[5]),
                               np.asarray(new_k[8:]))


# ---------------------------------------------------------------------------
# dispatch: counters, eligibility gate, escape leg, kernel-error fallback
# ---------------------------------------------------------------------------
def _eligible_shapes(seed=0):
    # S=128, D=64: inside the _paged_ok contract
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(2, 2, 64), jnp.float32)
    kp = jnp.asarray(rng.randn(5, 128, 2, 64), jnp.float32)
    vp = jnp.asarray(rng.randn(5, 128, 2, 64), jnp.float32)
    table = jnp.asarray([[1, 2], [3, -1]], jnp.int32)
    lens = jnp.asarray([200, 70], jnp.int32)
    return q, kp, vp, table, lens


def test_dispatch_pallas_bumps_counter(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    q, kp, vp, table, lens = _eligible_shapes()
    out = pa.paged_attention(q, kp, vp, table, lens)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert counters.snapshot().get("paged_attention.pallas", 0) == 1


def test_dispatch_ineligible_falls_back_with_counter(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    q, kp, vp = _pool()          # S=8: outside the page-size contract
    table = jnp.asarray([[1, 2, 3], [4, 5, -1], [6, -1, -1]], jnp.int32)
    lens = jnp.asarray([20, 11, 5], jnp.int32)
    out = pa.paged_attention(q, kp, vp, table, lens)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert counters.snapshot().get("paged_attention.xla", 0) == 1
    assert counters.snapshot().get("paged_attention.pallas", 0) == 0


def test_dispatch_kernel_error_falls_back(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)

    def boom(*a, **k):
        raise RuntimeError("mosaic said no")

    monkeypatch.setattr(pa, "_paged_attention_pallas", boom)
    q, kp, vp, table, lens = _eligible_shapes()
    out = pa.paged_attention(q, kp, vp, table, lens)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert counters.snapshot().get("paged_attention.xla", 0) == 1


def test_escape_env_pins_xla_bitwise(monkeypatch):
    """PADDLE_PAGED_ATTENTION=0 pins the gather path even on an
    eligible shape — and its output is bitwise the fallback's."""
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    monkeypatch.setenv("PADDLE_PAGED_ATTENTION", "0")
    q, kp, vp, table, lens = _eligible_shapes()
    out = pa.paged_attention(q, kp, vp, table, lens)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    assert np.asarray(out).tobytes() == np.asarray(ref).tobytes()
    assert counters.snapshot().get("paged_attention.pallas", 0) == 0
    assert counters.snapshot().get("paged_attention.xla", 0) == 1


def test_paged_ok_gate():
    class _Arr:
        def __init__(self, shape):
            self.shape = shape

    import paddle_tpu.ops.pallas.paged_attention as mod

    real = bringup.pallas_enabled
    try:
        bringup.pallas_enabled = lambda: True

        def ok(h, d, s):
            return mod._paged_ok(_Arr((2, h, d)), _Arr((4, s, h, d)))

        assert ok(4, 64, 128) and ok(8, 128, 256)
        assert not ok(4, 48, 128)       # head_dim % 64
        assert not ok(4, 64, 100)       # page_size % 128
        assert not ok(4, 512, 128)      # D ceiling
        assert not ok(4, 64, 2048)      # page VMEM ceiling
    finally:
        bringup.pallas_enabled = real


# ---------------------------------------------------------------------------
# autotune: paged verdict keys, memoization, disk persistence
# ---------------------------------------------------------------------------
@pytest.fixture
def _autotune_tmp(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
    autotune.reset()
    yield tmp_path
    autotune.reset()


def test_paged_cache_key_namespaced():
    key = autotune.paged_cache_key(4, 8, 128, 2, 64, jnp.float32)
    assert key[0] == "paged"
    assert key == ("paged", 4, 8, 128, 2, 64, str(jnp.float32))
    # distinct from any flash key shape and from other paged shapes
    assert autotune.paged_cache_key(4, 8, 128, 2, 64, jnp.bfloat16) != key
    assert autotune.paged_cache_key(8, 8, 128, 2, 64, jnp.float32) != key


def test_paged_choice_none_off_tpu(_autotune_tmp):
    q, kp, _, table, _ = _eligible_shapes()
    assert autotune.paged_attention_choice(q, kp, table) is None


def test_paged_selection_memoizes_and_persists(monkeypatch,
                                               _autotune_tmp):
    import paddle_tpu.utils.timing as timing

    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    monkeypatch.setattr(bringup, "TPU_PLATFORMS", ("cpu", "tpu"))
    times = iter([5.0, 1.0])    # pallas, xla -> xla wins
    calls = []

    def fake_timeit(fn, *a, **k):
        calls.append(fn)
        return next(times)

    monkeypatch.setattr(timing, "timeit", fake_timeit)
    q, kp, _, table, _ = _eligible_shapes()
    assert autotune.paged_attention_choice(q, kp, table) == "xla"
    assert len(calls) == 2
    # memoized: same shape re-queries pay nothing
    assert autotune.paged_attention_choice(q, kp, table) == "xla"
    assert len(calls) == 2
    # a fresh process (reset memo, keep disk) reads the persisted
    # verdict instead of re-timing
    autotune._cache.clear()
    autotune._disk = None
    monkeypatch.setattr(timing, "timeit",
                        lambda *a, **k: pytest.fail("re-timed a "
                                                    "persisted verdict"))
    assert autotune.paged_attention_choice(q, kp, table) == "xla"
    assert autotune.stats()["disk_hits"] >= 1


def test_paged_autotuned_xla_choice_drives_dispatch(monkeypatch,
                                                    _autotune_tmp):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    monkeypatch.setattr(bringup, "TPU_PLATFORMS", ("cpu", "tpu"))
    import paddle_tpu.utils.timing as timing

    monkeypatch.setattr(timing, "timeit",
                        lambda fn, *a, **k: {0: 9.0}.get(id(fn) % 1, 1.0))
    # force the verdict directly: dispatch must honor it with the
    # autotuned-xla counter reason
    q, kp, vp, table, lens = _eligible_shapes()
    key = autotune.paged_cache_key(q.shape[0], table.shape[1],
                                   kp.shape[1], q.shape[1], q.shape[2],
                                   q.dtype)
    autotune._cache[key] = "xla"
    out = pa.paged_attention(q, kp, vp, table, lens)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert counters.snapshot().get("paged_attention.xla", 0) == 1
    assert counters.snapshot().get("paged_attention.pallas", 0) == 0


# ---------------------------------------------------------------------------
# int8 KV pages (kv_codec="int8"): quant parity, writes, dispatch
# ---------------------------------------------------------------------------
from paddle_tpu.ps.codec import jnp_encode_kv_rows  # noqa: E402


def _quant_pool(**kw):
    q, kp, vp = _pool(**kw)
    kq, ks = jnp_encode_kv_rows(kp)
    vq, vs = jnp_encode_kv_rows(vp)
    return q, kq, vq, ks, vs, kp, vp


def test_quant_xla_tracks_f32_reference():
    """Dequantized attention stays within int8-roundoff of the f32
    pool — the kv_quant_loss gate at unit scale."""
    q, kq, vq, ks, vs, kp, vp = _quant_pool()
    table = jnp.asarray([[1, 2, 3], [4, 5, -1], [6, -1, -1]], jnp.int32)
    lens = jnp.asarray([20, 11, 5], jnp.int32)
    ref = pa._xla_paged_attention(q, kp, vp, table, lens)
    out = pa._xla_paged_attention_quant(q, kq, vq, ks, vs, table, lens)
    assert float(jnp.max(jnp.abs(out - ref))) <= 5e-2


def test_quant_kernel_matches_quant_xla():
    """The quant kernel and the quant gather fallback are the same
    function of the encoded pool."""
    q, kq, vq, ks, vs, _, _ = _quant_pool(seed=9)
    table = jnp.asarray([[1, 2, 3], [4, 5, -1], [6, -1, -1]], jnp.int32)
    lens = jnp.asarray([20, 11, 5], jnp.int32)
    ref = pa._xla_paged_attention_quant(q, kq, vq, ks, vs, table, lens)
    out = pa._paged_attention_pallas_quant(q, kq, vq, ks, vs, table,
                                           lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_write_quant_roundtrip_and_trash_page():
    """paged_write_quant encodes the row in place: payload at
    [page, off], its scale on the (P, S) plane, and inactive lanes
    land on the reserved page 0."""
    _, kp, vp = _pool(b=2, pages=6, t=2)
    kq, ks = jnp_encode_kv_rows(kp)
    vq, vs = jnp_encode_kv_rows(vp)
    table = jnp.asarray([[3, 4], [5, -1]], jnp.int32)
    positions = jnp.asarray([9, 2], jnp.int32)
    new_k = jnp.full((2, 2, 16), 7.0, jnp.float32)
    new_v = jnp.full((2, 2, 16), -7.0, jnp.float32)
    k2, v2, ks2, vs2 = pa.paged_write_quant(
        kq, vq, ks, vs, table, positions, new_k, new_v,
        jnp.asarray([True, True]))
    # dequant lands back on the written constant
    np.testing.assert_allclose(
        np.asarray(k2[4, 1].astype(jnp.float32) * ks2[4, 1]),
        7.0, rtol=1e-2)
    np.testing.assert_allclose(
        np.asarray(v2[5, 2].astype(jnp.float32) * vs2[5, 2]),
        -7.0, rtol=1e-2)
    # untouched elsewhere (payload AND scale planes)
    np.testing.assert_array_equal(np.asarray(k2[3]), np.asarray(kq[3]))
    np.testing.assert_array_equal(np.asarray(ks2[3]), np.asarray(ks[3]))
    # inactive lanes route to the trash page
    k3, _, ks3, _ = pa.paged_write_quant(
        kq, vq, ks, vs, table, positions, new_k, new_v,
        jnp.asarray([False, False]))
    np.testing.assert_array_equal(np.asarray(k3[1:]), np.asarray(kq[1:]))
    np.testing.assert_array_equal(np.asarray(ks3[1:]),
                                  np.asarray(ks[1:]))


def test_paged_prefill_write_quant_roundtrip():
    _, kp, vp = _pool(pages=8)
    kq, ks = jnp_encode_kv_rows(kp)
    vq, vs = jnp_encode_kv_rows(vp)
    page_ids = jnp.asarray([2, 5], jnp.int32)
    new_k = jnp.asarray(np.random.RandomState(4).randn(16, 2, 16),
                        jnp.float32)
    k2, _, ks2, _ = pa.paged_prefill_write_quant(kq, vq, ks, vs,
                                                 page_ids, new_k, new_k)
    deq = np.asarray(k2[2].astype(jnp.float32)) * \
        np.asarray(ks2[2])[:, None, None]
    np.testing.assert_allclose(deq, np.asarray(new_k[:8]), atol=0.05)
    deq5 = np.asarray(k2[5].astype(jnp.float32)) * \
        np.asarray(ks2[5])[:, None, None]
    np.testing.assert_allclose(deq5, np.asarray(new_k[8:]), atol=0.05)


def test_quant_dispatch_counters_and_escape(monkeypatch):
    monkeypatch.setattr(bringup, "pallas_enabled", lambda: True)
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 2, 64), jnp.float32)
    kp = jnp.asarray(rng.randn(5, 128, 2, 64), jnp.float32)
    vp = jnp.asarray(rng.randn(5, 128, 2, 64), jnp.float32)
    kq, ks = jnp_encode_kv_rows(kp)
    vq, vs = jnp_encode_kv_rows(vp)
    table = jnp.asarray([[1, 2], [3, -1]], jnp.int32)
    lens = jnp.asarray([200, 70], jnp.int32)
    out = pa.paged_attention(q, kq, vq, table, lens, k_scales=ks,
                             v_scales=vs)
    ref = pa._xla_paged_attention_quant(q, kq, vq, ks, vs, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert counters.snapshot().get("paged_attention.pallas", 0) == 1
    # the escape env pins the quant gather path bitwise
    monkeypatch.setenv("PADDLE_PAGED_ATTENTION", "0")
    out2 = pa.paged_attention(q, kq, vq, table, lens, k_scales=ks,
                              v_scales=vs)
    assert np.asarray(out2).tobytes() == np.asarray(ref).tobytes()
    assert counters.snapshot().get("paged_attention.xla", 0) == 1
