"""Vision transform breadth (reference hapi/vision/transforms:
transforms.py + functional.py)."""
import numpy as np
import pytest

from paddle_tpu.vision import transforms as T


def _img(h=16, w=12, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (h, w, c)).astype(np.uint8)


def test_functional_flip_resize_pad():
    img = _img()
    np.testing.assert_array_equal(T.flip(img, 1), img[:, ::-1])
    np.testing.assert_array_equal(T.flip(img, 0), img[::-1])
    np.testing.assert_array_equal(T.flip(img, -1), img[::-1, ::-1])
    assert T.resize(img, (8, 8)).shape == (8, 8, 3)
    padded = T.pad(img, (1, 2, 3, 4))          # l, t, r, b
    assert padded.shape == (16 + 2 + 4, 12 + 1 + 3, 3)


def test_rotate_identity_and_90():
    img = _img(8, 8)
    np.testing.assert_array_equal(T.rotate(img, 0), img)
    r90 = T.rotate(img.astype(np.float32), 90)
    # rotating a symmetric pattern: just check shape + content moved
    assert r90.shape == img.shape
    assert not np.array_equal(r90, img)


def test_grayscale_weights():
    img = np.zeros((4, 4, 3), np.float32)
    img[..., 0] = 100.0                       # pure red
    g = T.to_grayscale(img)
    np.testing.assert_allclose(g[..., 0], 29.9, rtol=1e-3)
    g3 = T.Grayscale(3)(img)
    assert g3.shape == (4, 4, 3)
    assert np.allclose(g3[..., 0], g3[..., 1])


def test_random_resized_crop_and_center_crop_resize():
    np.random.seed(0)
    img = _img(32, 32)
    out = T.RandomResizedCrop(16)(img)
    assert out.shape == (16, 16, 3)
    out = T.CenterCropResize(16)(img)
    assert out.shape == (16, 16, 3)


def test_vertical_flip_and_permute():
    img = _img()
    np.random.seed(0)
    flipped = T.RandomVerticalFlip(prob=1.0)(img)
    np.testing.assert_array_equal(flipped, img[::-1])
    chw = T.Permute()(img)
    assert chw.shape == (3, 16, 12)


def test_color_transforms_change_pixels_but_keep_shape():
    np.random.seed(1)
    img = _img()
    for t in [T.BrightnessTransform(0.5), T.ContrastTransform(0.5),
              T.SaturationTransform(0.5), T.HueTransform(0.3),
              T.ColorJitter(0.4, 0.4, 0.4, 0.2), T.GaussianNoise(0, 5.0)]:
        out = t(img)
        assert np.asarray(out).shape == img.shape, type(t)
    with pytest.raises(ValueError):
        T.BrightnessTransform(-1)
    with pytest.raises(ValueError):
        T.HueTransform(0.9)


def test_hue_zero_value_is_identity_and_rotation_reversible():
    img = _img()
    np.testing.assert_array_equal(T.HueTransform(0)(img), img)


def test_random_erasing():
    np.random.seed(3)
    img = np.ones((16, 16, 3), np.float32)
    out = T.RandomErasing(prob=1.0)(img)
    assert (out == 0).any()
    assert out.shape == img.shape
    # prob=0 is identity
    np.testing.assert_array_equal(T.RandomErasing(prob=0.0)(img), img)


def test_batch_compose():
    bc = T.BatchCompose([lambda batch: [b * 2 for b in batch]])
    out = bc([np.ones(2), np.ones(2)])
    np.testing.assert_array_equal(out[0], [2.0, 2.0])


def test_lr_fluid_aliases():
    from paddle_tpu.optimizer import lr
    assert issubclass(lr.CosineDecay, lr.LRScheduler)
    assert lr.LinearLrWarmup is lr.LinearWarmup
    assert lr.ReduceLROnPlateau is lr.ReduceOnPlateau


def test_cosine_decay_fluid_signature():
    """fluid CosineDecay(lr, step_each_epoch, epochs) semantics (review
    regression: was aliased to CosineAnnealingDecay)."""
    from paddle_tpu.optimizer import lr
    import math
    sched = lr.CosineDecay(0.1, step_each_epoch=10, epochs=4)
    assert abs(sched.get_lr() - 0.1) < 1e-9          # epoch 0
    for _ in range(10):
        sched.step()
    expected = 0.05 * (math.cos(math.pi / 4) + 1)
    assert abs(sched.get_lr() - expected) < 1e-9


def test_rotate_expand_and_center():
    img = np.ones((10, 20, 3), np.float32)
    out = T.rotate(img, 90, expand=True)
    assert out.shape[0] >= 19 and out.shape[1] >= 9   # canvas grew
    same = T.rotate(img, 0, center=(5, 5))
    np.testing.assert_array_equal(same, img)


def test_permute_bgr_to_rgb():
    img = np.zeros((2, 2, 3), np.uint8)
    img[..., 0] = 10   # B
    img[..., 2] = 30   # R
    chw = T.Permute(to_rgb=True)(img)
    assert chw[0, 0, 0] == 30 and chw[2, 0, 0] == 10
    chw2 = T.Permute(to_rgb=False)(img)
    assert chw2[0, 0, 0] == 10


def test_resize_interpolation_modes():
    mask = np.array([[0, 0], [3, 3]], np.float32)
    out = T.resize(mask, (4, 4), interpolation="nearest")
    assert set(np.unique(out)) <= {0.0, 3.0}           # no blended labels
    with pytest.raises(ValueError):
        T.Resize(4, interpolation="area")

