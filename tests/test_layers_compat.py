"""fluid.layers compatibility bridge (static/layers_compat.py): graph-
built LR schedules, loss/sequence/detection delegates, RNN sweep ops,
hsigmoid/warpctc/hash/auc — executed through Program/Executor."""
import math

import numpy as np
import pytest

import paddle_tpu.static as static

pytestmark = pytest.mark.slow


def _run(build, feeds=None):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        outs = build()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
    exe = static.Executor()
    exe.run(startup)
    return [np.asarray(r) for r in
            exe.run(main, feed=feeds or {}, fetch_list=list(outs))], \
        (exe, main)


def test_graph_built_lr_schedule_drives_optimizer():
    """exponential_decay builds a Variable from the step counter; the
    optimizer consumes it and the fetched lr follows the closed form
    across exe.run calls (reference learning_rate_scheduler.py)."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [8, 4])
        yv = static.data("y", [8, 1])
        lr = static.exponential_decay(0.1, decay_steps=2, decay_rate=0.5)
        loss = static.reduce_mean(
            static.square_error_cost(static.nn.fc(xv, 1), yv))
        static.SGD(learning_rate=lr).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    lrs = []
    for _ in range(5):
        out = exe.run(main, feed={"x": x, "y": y}, fetch_list=[lr, loss])
        lrs.append(float(np.asarray(out[0]).ravel()[0]))
    # step counter starts at 1 on the first run
    want = [0.1 * 0.5 ** ((i + 1) / 2) for i in range(5)]
    np.testing.assert_allclose(lrs, want, rtol=1e-5)


def test_more_lr_schedules_build_and_run():
    def build():
        return [static.noam_decay(64, 10),
                static.natural_exp_decay(0.1, 5, 0.5),
                static.inverse_time_decay(0.1, 5, 0.5),
                static.polynomial_decay(0.1, 10),
                static.piecewise_decay([2, 5], [0.1, 0.05, 0.01]),
                static.cosine_decay(0.1, 2, 10),
                static.linear_lr_warmup(0.1, 5, 0.0, 0.1)]

    outs, _ = _run(build)
    step = 1.0  # first run
    assert abs(float(outs[1]) - 0.1 * math.exp(-0.5 * step / 5)) < 1e-6
    assert abs(float(outs[2]) - 0.1 / (1 + 0.5 * step / 5)) < 1e-6
    assert abs(float(outs[4]) - 0.1) < 1e-7          # step 1 < boundary 2
    assert abs(float(outs[6]) - (0.1 / 5)) < 1e-6    # warmup step 1


def test_loss_delegates_values():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 3).astype(np.float32)
    y = rng.rand(4, 3).astype(np.float32)
    logits = rng.randn(4, 3).astype(np.float32)
    bin_lbl = rng.randint(0, 2, (4, 3)).astype(np.float32)

    def build():
        a = static.data("x", [4, 3])
        b = static.data("y", [4, 3])
        lg = static.data("lg", [4, 3])
        bl = static.data("bl", [4, 3])
        return [static.mse_loss(a, b), static.huber_loss(a, b, 0.5),
                static.sigmoid_cross_entropy_with_logits(lg, bl),
                static.kldiv_loss(a, b)]

    outs, _ = _run(build, {"x": x, "y": y, "lg": logits, "bl": bin_lbl})
    np.testing.assert_allclose(outs[0], np.mean((x - y) ** 2), rtol=1e-5)
    want_ce = np.maximum(logits, 0) - logits * bin_lbl + \
        np.log1p(np.exp(-np.abs(logits)))
    np.testing.assert_allclose(outs[2], want_ce, rtol=1e-5, atol=1e-6)


def test_sigmoid_focal_loss_down_weights_easy():
    x = np.array([[5.0, -5.0], [-5.0, 5.0]], np.float32)   # confident
    lbl = np.array([[1], [2]], np.int64)                   # correct

    def build():
        xv = static.data("x", [2, 2])
        lv = static.data("l", [2, 1], dtype="int64")
        return static.sigmoid_focal_loss(xv, lv)

    outs, _ = _run(build, {"x": x, "l": lbl})
    assert np.all(outs[0] < 0.01)      # easy correct -> tiny loss


def test_detection_delegates():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)

    def build():
        f = static.data("f", [1, 8, 4, 4])
        im = static.data("im", [1, 3, 64, 64])
        boxes, var = static.prior_box(f, im, min_sizes=[16.0],
                                      aspect_ratios=[1.0])
        anchors, avar = static.anchor_generator(
            f, anchor_sizes=[32.0], aspect_ratios=[1.0, 2.0])
        a = static.data("ba", [3, 4])
        b = static.data("bb", [2, 4])
        iou = static.iou_similarity(a, b)
        return [boxes, anchors, iou]

    ba = np.array([[0, 0, 1, 1], [0, 0, 2, 2], [5, 5, 6, 6]], np.float32)
    bb = np.array([[0, 0, 1, 1], [1, 1, 2, 2]], np.float32)
    outs, _ = _run(build, {"f": feat, "im": img, "ba": ba, "bb": bb})
    assert outs[0].shape[-1] == 4
    assert outs[1].shape == (4, 4, 2, 4)
    assert abs(outs[2][0, 0] - 1.0) < 1e-6     # identical boxes IoU=1


def test_hash_range_auc():
    ids = np.array([[1, 2], [3, 1]], np.int64)

    def build():
        iv = static.data("ids", [2, 2], dtype="int64")
        h = static.hash(iv, hash_size=100, num_hash=2)
        r = static.range(0, 10, 2, "int64")
        p = static.data("p", [6, 2])
        lbl = static.data("lbl", [6, 1], dtype="int64")
        a = static.auc(p, lbl)
        return [h, r, a]

    p = np.stack([1 - np.array([.9, .8, .7, .3, .2, .1]),
                  np.array([.9, .8, .7, .3, .2, .1])], 1).astype(np.float32)
    lbl = np.array([[1], [1], [0], [1], [0], [0]], np.int64)
    outs, _ = _run(build, {"ids": ids, "p": p, "lbl": lbl})
    assert outs[0].shape == (2, 2, 2)
    assert (outs[0] >= 0).all() and (outs[0] < 100).all()
    # determinism: same id -> same hash
    assert outs[0][0, 0, 0] == outs[0][1, 1, 0]
    np.testing.assert_array_equal(outs[1], np.arange(0, 10, 2))
    # manual AUC: pos ranks {6,5,2} of 6 -> (13 - 6)/ (3*3)
    assert abs(float(outs[2]) - 8.0 / 9.0) < 1e-5


def test_warpctc_loss_and_grads():
    rng = np.random.RandomState(0)
    B, T, C, L = 2, 8, 5, 3
    logits = rng.randn(B, T, C).astype(np.float32)
    labels = np.array([[1, 2, 3], [2, 4, 0]], np.int64)
    llen = np.array([3, 2], np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        lg = static.data("lg", [B, T, C])
        lb = static.data("lb", [B, L], dtype="int64")
        ll = static.data("ll", [B], dtype="int64")
        loss = static.warpctc(lg, lb, blank=0, label_length=ll)
        total = static.reduce_mean(loss)
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"lg": logits, "lb": labels, "ll": llen},
                  fetch_list=[loss, total])
    losses = np.asarray(out[0])
    assert losses.shape == (B, 1) and (losses > 0).all()

    import optax
    import jax.numpy as jnp

    tpos = np.arange(T)[None, :].repeat(B, 0)
    want = optax.ctc_loss(jnp.asarray(logits),
                          jnp.zeros((B, T), jnp.float32),
                          jnp.asarray(labels),
                          jnp.asarray((np.arange(L)[None, :] >=
                                       llen[:, None]).astype(np.float32)),
                          blank_id=0)
    np.testing.assert_allclose(losses.ravel(), np.asarray(want), rtol=1e-4)


def test_hsigmoid_trains():
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 6, (16, 1)).astype(np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [16, 8])
        yv = static.data("y", [16, 1], dtype="int64")
        loss = static.reduce_mean(static.hsigmoid(xv, yv, 6))
        static.SGD(0.5).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    vals = [float(np.asarray(exe.run(main, feed={"x": x, "y": y},
                                     fetch_list=[loss])[0]))
            for _ in range(20)]
    assert vals[-1] < vals[0] * 0.7, vals


def test_dynamic_lstm_gru_match_numpy():
    rng = np.random.RandomState(0)
    B, T, H = 2, 5, 4
    xl = rng.randn(B, T, 4 * H).astype(np.float32) * 0.5
    xg = rng.randn(B, T, 3 * H).astype(np.float32) * 0.5

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xlv = static.data("xl", [B, T, 4 * H])
        xgv = static.data("xg", [B, T, 3 * H])
        hid, cell = static.dynamic_lstm(xlv, 4 * H)
        gh = static.dynamic_gru(xgv, H)
    exe = static.Executor()
    exe.run(startup)
    from paddle_tpu.static.executor import global_scope

    hidv, cellv, ghv = [np.asarray(v) for v in exe.run(
        main, feed={"xl": xl, "xg": xg}, fetch_list=[hid, cell, gh])]
    # numpy LSTM reference with the trained-in (initialized) weights
    wname = [n for n in main.global_block.vars
             if n.startswith("dynamic_lstm_s_w")][0]
    w = np.asarray(global_scope().find_var(wname))
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    for t in range(T):
        g = xl[:, t] + h @ w
        i, f, cand, o = (1 / (1 + np.exp(-g[:, :H])),
                         1 / (1 + np.exp(-g[:, H:2 * H])),
                         np.tanh(g[:, 2 * H:3 * H]),
                         1 / (1 + np.exp(-g[:, 3 * H:])))
        c = f * c + i * cand
        h = o * np.tanh(c)
    np.testing.assert_allclose(hidv[:, -1], h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cellv[:, -1], c, rtol=1e-4, atol=1e-5)
    assert ghv.shape == (B, T, H)


def test_dynamic_lstm_lengths_freeze():
    B, T, H = 2, 6, 3
    rng = np.random.RandomState(1)
    x = rng.randn(B, T, 4 * H).astype(np.float32)
    lens = np.array([6, 2], np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [B, T, 4 * H])
        lv = static.data("lens", [B], dtype="int64")
        hid, _ = static.dynamic_lstm(xv, 4 * H, lengths=lv)
    exe = static.Executor()
    exe.run(startup)
    out = np.asarray(exe.run(main, feed={"x": x, "lens": lens},
                             fetch_list=[hid])[0])
    # row 1 freezes after t=2: all later steps equal h at t=1
    np.testing.assert_allclose(out[1, 2:], np.broadcast_to(
        out[1, 1], out[1, 2:].shape), atol=1e-6)


def test_lstm_multilayer_and_units():
    B, T, D, H = 2, 4, 6, 5
    rng = np.random.RandomState(2)
    x = rng.randn(B, T, D).astype(np.float32)
    h0 = np.zeros((2, B, H), np.float32)
    c0 = np.zeros((2, B, H), np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [B, T, D])
        hv = static.data("h0", [2, B, H])
        cv = static.data("c0", [2, B, H])
        out, lh, lc = static.lstm(xv, hv, cv, T, H, num_layers=2)
        # single-step units
        xu = static.data("xu", [B, 3 * H])
        hu = static.data("hu", [B, H])
        gh, _, _ = static.gru_unit(xu, hu, 3 * H)
        xr = static.data("xr", [B, D])
        cu = static.data("cu", [B, H])
        uh, uc = static.lstm_unit(xr, hu, cu)
    exe = static.Executor()
    exe.run(startup)
    outs = exe.run(main, feed={
        "x": x, "h0": h0, "c0": c0,
        "xu": rng.randn(B, 3 * H).astype(np.float32),
        "hu": np.zeros((B, H), np.float32),
        "xr": rng.randn(B, D).astype(np.float32),
        "cu": np.zeros((B, H), np.float32)},
        fetch_list=[out, gh, uh, uc])
    assert np.asarray(outs[0]).shape == (B, T, H)
    assert np.asarray(outs[1]).shape == (B, H)
    assert np.asarray(outs[2]).shape == (B, H)


def test_chunk_eval_iob():
    from paddle_tpu.static import chunk_eval

    # IOB, 2 types: tags B0=0 I0=1 B1=2 I1=3 O=4
    label = np.array([[0, 1, 4, 2, 3, 4]])
    pred = np.array([[0, 1, 4, 2, 4, 4]])   # second chunk truncated
    p, r, f1, ni, nl, nc = chunk_eval(pred, label, "IOB", 2)
    assert (ni, nl, nc) == (2, 2, 1)
    assert abs(f1 - 0.5) < 1e-9


def test_multi_box_head_shapes():
    def build():
        f1 = static.data("f1", [1, 8, 4, 4])
        f2 = static.data("f2", [1, 8, 2, 2])
        img = static.data("img", [1, 3, 64, 64])
        locs, confs, boxes, vars_ = static.multi_box_head(
            [f1, f2], img, base_size=64, num_classes=3,
            aspect_ratios=[[1.0], [1.0, 2.0]], min_ratio=20, max_ratio=90)
        return [locs, confs, boxes, vars_]

    outs, _ = _run(build, {"f1": np.zeros((1, 8, 4, 4), np.float32),
                           "f2": np.zeros((1, 8, 2, 2), np.float32),
                           "img": np.zeros((1, 3, 64, 64), np.float32)})
    P = outs[2].shape[0]
    assert outs[0].shape == (1, P, 4)
    assert outs[1].shape == (1, P, 3)
    assert outs[3].shape == (P, 4)


def test_yolov3_loss_trains():
    rng = np.random.RandomState(0)
    B, an, C, HW = 1, 2, 3, 4
    anchors = [10, 14, 23, 27]
    x = rng.randn(B, an * (5 + C), HW, HW).astype(np.float32) * 0.1
    gt_box = np.array([[[0.4, 0.4, 0.2, 0.3]]], np.float32)
    gt_label = np.array([[1]], np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [B, an * (5 + C), HW, HW])
        xv.desc.stop_gradient = False
        gb = static.data("gb", [B, 1, 4])
        gl = static.data("gl", [B, 1], dtype="int64")
        h = static.nn.conv2d(xv, an * (5 + C), 1)
        loss = static.reduce_mean(static.yolov3_loss(
            h, gb, gl, anchors, [0, 1], C, 0.7, 8))
        static.Adam(0.01).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    vals = [float(np.asarray(exe.run(
        main, feed={"x": x, "gb": gt_box, "gl": gt_label},
        fetch_list=[loss])[0])) for _ in range(40)]
    assert vals[-1] < vals[0] * 0.7, vals
    assert all(b <= a + 1e-4 for a, b in zip(vals, vals[1:])), vals


def test_sequence_compat_ops():
    x = np.arange(12, dtype=np.float32).reshape(2, 6)
    lens = np.array([6, 3], np.int64)

    def build():
        xv = static.data("x", [2, 6])
        lv = static.data("lens", [2], dtype="int64")
        m = static.sequence_mask(lv, maxlen=6)
        r = static.sequence_reshape(xv, 3)
        return [m, r]

    outs, _ = _run(build, {"x": x, "lens": lens})
    np.testing.assert_array_equal(
        outs[0], (np.arange(6)[None, :] < lens[:, None]).astype(np.int64))
    assert outs[1].shape == (2, 2, 3)


def test_nce_and_sampled_softmax_train():
    rng = np.random.RandomState(0)
    B, D, C = 16, 8, 20
    x = rng.randn(B, D).astype(np.float32)
    y = rng.randint(0, C, (B, 1)).astype(np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = static.data("x", [B, D])
        yv = static.data("y", [B, 1], dtype="int64")
        nce_loss = static.reduce_mean(static.nce(xv, yv, C))
        logits = static.nn.fc(xv, C)
        sce = static.reduce_mean(
            static.sampled_softmax_with_cross_entropy(logits, yv, 5))
        loss = static.elementwise_add(nce_loss, sce)
        static.Adam(0.05).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    vals = [float(np.asarray(exe.run(main, feed={"x": x, "y": y},
                                     fetch_list=[loss])[0]))
            for _ in range(15)]
    assert vals[-1] < vals[0], vals
