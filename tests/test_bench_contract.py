"""Driver-contract regression tests for bench.py and backend bring-up.

Rounds 1 and 2 both lost their perf evidence to the same failure mode: a
dead remote-TPU tunnel (a PJRT plugin whose factory hangs) made
`jax.devices()` block, the old 75 s probe burned most of the budget, and
the degraded path then benched full-size BERT on CPU until the driver's
`timeout` killed it (rc=124, nothing parseable). These tests simulate the
dead tunnel with a sitecustomize-registered hanging PJRT factory and pin
the contract: `python bench.py` must print a parseable JSON row quickly
and exit 0 under ANY tunnel state.

Reference posture being matched:
/root/reference/paddle/fluid/platform/init.cc (InitDevices never fails
the process), platform/dynload/dynamic_loader.cc (degrade on missing
driver).
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

# Registers a PJRT backend factory that blocks forever — the exact shape
# of the dead-axon-tunnel hang. fail_quietly only covers *raising*
# factories, so jax's first backends() call blocks on this one.
SITECUSTOMIZE = """\
import time


def _install():
    try:
        from jax._src import xla_bridge as xb
    except Exception:
        return

    def factory():
        time.sleep(3600)

    try:
        xb.register_backend_factory("faketunnel", factory, priority=400)
    except Exception:
        pass


_install()
"""


def _dead_tunnel_env(tmp_path, **extra):
    site_dir = tmp_path / "site"
    site_dir.mkdir(exist_ok=True)
    (site_dir / "sitecustomize.py").write_text(SITECUSTOMIZE)
    env = dict(os.environ)
    # the hang must be reachable: drop the test suite's cpu pin
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = f"{site_dir}{os.pathsep}{env.get('PYTHONPATH', '')}"
    env["PADDLE_TPU_PROBE_TIMEOUT"] = "5"
    env["PADDLE_TPU_PROBE_CACHE"] = str(tmp_path / "probe_cache.json")
    env.update(extra)
    return env


def _run_streaming(cmd, env, first_row_deadline, total_deadline):
    """Run cmd; return (rc, lines, seconds_to_first_json_line)."""
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    lines, first_at = [], [None]
    t0 = time.monotonic()

    def reader():
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{") and first_at[0] is None:
                first_at[0] = time.monotonic() - t0
            if line:
                lines.append(line)

    th = threading.Thread(target=reader, daemon=True)
    th.start()
    try:
        rc = proc.wait(timeout=total_deadline)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        pytest.fail(f"bench.py exceeded {total_deadline}s; "
                    f"captured lines: {lines}")
    th.join(timeout=10)
    assert first_at[0] is not None, f"no JSON line in output: {lines}"
    assert first_at[0] < first_row_deadline, (
        f"first JSON row took {first_at[0]:.1f}s "
        f"(limit {first_row_deadline}s)")
    return rc, lines, first_at[0]


def test_bench_emits_row_fast_with_dead_tunnel(tmp_path):
    """Dead tunnel + tiny overrides: a parseable row in <60 s, rc 0."""
    captures = tmp_path / "captures.jsonl"
    env = _dead_tunnel_env(tmp_path, BENCH_LAYERS="1", BENCH_BATCH="2",
                           BENCH_SEQ="16", BENCH_STEPS="1",
                           BENCH_NO_PERSIST="0",
                           BENCH_CAPTURES_PATH=str(captures))
    # total deadline covers the in-process probes PLUS the multichip
    # subprocess probe (fresh interpreter + 8-virtual-device compiles)
    rc, lines, _ = _run_streaming(
        [sys.executable, BENCH], env,
        first_row_deadline=60, total_deadline=240)
    assert rc == 0
    rows = [json.loads(ln) for ln in lines if ln.startswith("{")]
    assert rows, lines
    # VERDICT r3 weak #1: every measured row must leave a durable capture
    # (ts + git sha + backend), so live-TPU numbers survive as artifacts
    caps = [json.loads(ln) for ln in
            captures.read_text().strip().splitlines()]
    assert caps, "measured row was not persisted to BENCH_CAPTURES"
    assert all(c.get("placeholder") is None for c in caps), caps
    cap = caps[-1]
    assert cap["kind"] == "bench" and cap["ts"] and cap["git_sha"]
    assert cap["backend"] == "cpu" and cap["config"] == "bert"
    assert cap["value"] == rows[-1]["value"]
    # the placeholder precedes the measurement; the LAST row is the one
    # the driver parses and it must carry the headline metric
    last = rows[-1]
    assert last["metric"] == "bert_base_pretrain_tokens_per_sec_per_chip"
    assert last["backend"] == "cpu"
    assert last.get("comparable") is False
    assert rows[0].get("placeholder") is True
    # provenance: no driver-captured baseline exists yet, so no ratio
    assert last.get("baseline_provenance") in ("none", None)
    # IR pass pipeline contract: the bert row carries the static-graph
    # probe's op-count reduction (bitwise-parity-gated), the AOT
    # trace/compile split, and the disk-cache counter
    for key in ("ops_before", "ops_after", "trace_ms", "compile_ms",
                "disk_cache_hits"):
        assert key in last, f"bench row missing {key!r}"
    assert last["ops_after"] < last["ops_before"], last
    assert last.get("pass_parity_bitwise") is True, last
    assert last.get("exec_cache_shared_hit") is True, last
    # no PADDLE_COMPILE_CACHE_DIR in this run -> no disk traffic
    assert last["disk_cache_hits"] == 0
    # graph-derived cost model cross-check: the IR-walked flop count of
    # the bert-shaped probe agrees with the closed-form flops_per_step
    # within 2% (the two accountings can never silently drift)
    for key in ("ir_flops_per_step", "ir_flops_delta"):
        assert key in last, f"bench row missing {key!r}"
    assert last["ir_flops_per_step"] > 0, last
    assert last["ir_flops_delta"] <= 0.02, last
    # mixed-precision probe contract: amp-on runs end to end, the loss
    # delta vs f32 stays within roundoff tolerance, casts were inserted
    # and the bf16 feed path really shrank the h2d transfer
    for key in ("amp_tokens_per_sec", "amp_loss_delta",
                "amp_casts_inserted", "amp_casts_elided",
                "amp_master_params", "amp_h2d_bytes",
                "amp_f32_h2d_bytes"):
        assert key in last, f"bench row missing {key!r}"
    assert last["amp_tokens_per_sec"] > 0, last
    assert last["amp_loss_delta"] <= 1e-2, last
    assert last["amp_casts_inserted"] > 0, last
    assert last["amp_master_params"] > 0, last
    assert last["amp_h2d_bytes"] < last["amp_f32_h2d_bytes"], last
    # rematerialization probe contract: XLA temp/peak bytes strictly
    # drop with remat on, at BITWISE-identical loss (dropout replay
    # inside recomputed segments); gradient_merge_k=4 covers 4
    # microbatches per compiled dispatch within 1e-5 of unmerged f32
    for key in ("remat_temp_bytes", "f32_temp_bytes", "remat_peak_bytes",
                "f32_peak_bytes", "gm_tokens_per_sec", "memory_stats",
                "gm_loss_delta"):
        assert key in last, f"bench row missing {key!r}"
    assert last["remat_temp_bytes"] < last["f32_temp_bytes"], last
    assert last["remat_peak_bytes"] < last["f32_peak_bytes"], last
    assert last.get("remat_parity_bitwise") is True, last
    assert last["remat_segments"] > 1, last
    assert last["gm_loss_delta"] <= 1e-5, last
    assert last["gm_k"] == 4 and last["gm_microbatches"] == \
        4 * last["gm_dispatches"], last
    for key in ("temp_bytes", "peak_bytes", "argument_bytes"):
        assert last["memory_stats"].get(key, 0) > 0, last["memory_stats"]
    # serving probe contract: the continuous-batching engine served the
    # whole closed-loop run — with faults off at nominal load, ZERO
    # requests shed, deadline-expired, degraded, or failed — and reports
    # throughput, tail latency, and batch fill
    for key in ("serve_requests_per_sec", "serve_p50_ms", "serve_p99_ms",
                "serve_requests", "serve_batches", "serve_shed",
                "serve_deadline_expired", "serve_degraded",
                "serve_failed", "serve_batch_fill_pct", "serve_ok"):
        assert key in last, f"bench row missing {key!r}"
    assert last["serve_requests_per_sec"] > 0, last
    assert last["serve_p99_ms"] >= last["serve_p50_ms"] > 0, last
    # engine-side latency truth: the bucket-derived percentiles the
    # engine's serve_e2e_ms / serve_queue_wait_ms histograms report —
    # load_gen's client view is no longer the only latency record
    for key in ("serve_engine_p50_ms", "serve_engine_p99_ms",
                "serve_queue_wait_p50_ms", "serve_queue_wait_p99_ms",
                "serve_client_p50_ms", "serve_client_p99_ms"):
        assert key in last, f"bench row missing {key!r}"
    assert last["serve_engine_p99_ms"] >= last["serve_engine_p50_ms"] > 0, \
        last
    assert last["serve_queue_wait_p99_ms"] >= \
        last["serve_queue_wait_p50_ms"] >= 0, last
    assert last["serve_client_p99_ms"] >= last["serve_client_p50_ms"] > 0, \
        last
    assert last["serve_ok"] == last["serve_requests"] > 0, last
    assert last["serve_shed"] == 0, last
    assert last["serve_deadline_expired"] == 0, last
    assert last["serve_degraded"] == 0 and last["serve_failed"] == 0, last
    assert 0 < last["serve_batch_fill_pct"] <= 100.0, last
    assert last["serve_batches"] <= last["serve_requests"], last
    # LLM decode probe contract: the paged continuous-batching engine
    # beats the padded-bucket data path ON THE SAME MODEL at mixed
    # lengths with IDENTICAL greedy outputs, engine-side p50/p99 come
    # from the decode histograms' buckets, and with faults off at
    # nominal load nothing sheds/expires/fails
    for key in ("decode_tokens_per_sec", "decode_padded_tokens_per_sec",
                "decode_padded_parity", "decode_engine_p50_ms",
                "decode_engine_p99_ms", "decode_step_p50_ms",
                "decode_step_p99_ms", "decode_ttft_p50_ms",
                "decode_requests", "decode_tokens", "decode_prefills",
                "decode_steps", "decode_shed", "decode_deadline_expired",
                "decode_failed", "decode_batch_fill_pct",
                "decode_page_util_peak_pct", "kv_page_evictions",
                "decode_ok", "trace_spans_per_request",
                "decode_slowest_trace", "decode_slowest_trace_ms"):
        assert key in last, f"bench row missing {key!r}"
    assert last["decode_tokens_per_sec"] > 0, last
    # the acceptance gate: ragged paged decode beats padded recompute
    assert last["decode_tokens_per_sec"] > \
        last["decode_padded_tokens_per_sec"] > 0, last
    assert last["decode_padded_parity"] is True, last
    assert last["decode_engine_p99_ms"] >= last["decode_engine_p50_ms"] \
        > 0, last
    assert last["decode_step_p99_ms"] >= last["decode_step_p50_ms"] > 0, \
        last
    assert last["decode_ok"] == last["decode_requests"] > 0, last
    assert last["decode_tokens"] > 0 and last["decode_steps"] > 0, last
    assert last["decode_shed"] == 0, last
    assert last["decode_deadline_expired"] == 0, last
    assert last["decode_failed"] == 0, last
    assert 0 < last["decode_batch_fill_pct"] <= 100.0, last
    assert 0 < last["decode_page_util_peak_pct"] <= 100.0, last
    # tracing contract: the probe runs traced — every served request
    # leaves at least its client root + decode.request + queue +
    # prefill spans, and the slowest request is named by trace id
    assert last["trace_spans_per_request"] >= 3.0, last
    assert isinstance(last["decode_slowest_trace"], str) \
        and len(last["decode_slowest_trace"]) == 16, last
    assert last["decode_slowest_trace_ms"] > 0, last
    # decode token-economics contract: speculative decoding is EXACT
    # under greedy (spec_parity) and pays for itself (every accepted
    # draft token is a ragged step never run → strictly fewer steps
    # and more tokens/sec than the spec-off leg); int8 KV pages stay
    # inside the quant-loss gate at ~2x+ pool headroom; the repeated
    # prompt hits the shared-prefix index
    for key in ("spec_tokens_per_sec", "spec_accept_rate", "spec_steps",
                "spec_proposed", "spec_accepted", "spec_parity",
                "spec_beats_dense", "kv_quant_loss_delta",
                "kv_pool_headroom_x", "kv_prefix_hits",
                "kv_prefix_parity"):
        assert key in last, f"bench row missing {key!r}"
    assert last["spec_parity"] is True, last
    assert last["spec_proposed"] >= last["spec_accepted"] > 0, last
    assert last["spec_accept_rate"] > 0, last
    assert last["spec_steps"] < last["decode_steps"], last
    assert last["spec_beats_dense"] is True, last
    assert last["spec_tokens_per_sec"] > \
        last["decode_tokens_per_sec"], last
    assert 0 <= last["kv_quant_loss_delta"] <= 5e-2, last
    assert last["kv_pool_headroom_x"] >= 2.0, last
    assert last["kv_prefix_hits"] > 0, last
    assert last["kv_prefix_parity"] is True, last
    # overlapped decode data plane contract (ISSUE 20): the async
    # double-buffered tick loop is EXACT under greedy (async_parity,
    # byte-identical outputs vs the PADDLE_ASYNC_DECODE=0 twin) and
    # wins the majority of paired rounds against it; the host-RAM KV
    # tier holds more concurrent sessions than the HBM pool alone
    # could (kv_sessions_per_pool_x > 1), park/resume is invisible in
    # the tokens, and the int8 host rows save most of the f32 bytes
    for key in ("async_tokens_per_sec", "sync_tokens_per_sec",
                "async_parity", "async_beats_sync", "async_round_wins",
                "decode_overlap_frac", "kv_sessions_per_pool_x",
                "kv_offload_parity", "kv_offload_bytes_saved_pct",
                "kv_offload_bytes", "kv_sessions_parked",
                "kv_sessions_resumed", "kv_page_restores"):
        assert key in last, f"bench row missing {key!r}"
    assert last["async_parity"] is True, last
    assert last["async_beats_sync"] is True, last
    assert last["async_tokens_per_sec"] > 0, last
    assert last["sync_tokens_per_sec"] > 0, last
    assert 0.0 < last["decode_overlap_frac"] <= 1.0, last
    assert last["kv_sessions_per_pool_x"] > 1.0, last
    assert last["kv_offload_parity"] is True, last
    assert last["kv_offload_bytes_saved_pct"] > 50.0, last
    assert last["kv_offload_bytes"] > 0, last
    assert last["kv_sessions_parked"] >= 1, last
    assert last["kv_sessions_resumed"] >= 1, last
    assert last["kv_page_restores"] >= 1, last
    # FLEET probe contract: two engines behind the serving router —
    # the zipf-session workload reports throughput + p99 TTFT, the
    # deterministic mid-generation engine stop fails over with the
    # survivor's greedy replay BITWISE equal to the dense oracle, and
    # KV page migration both saves wire bytes (int8 frame vs f32) and
    # degrades cleanly when the transport is dead (fallback counted)
    for key in ("fleet_tokens_per_sec", "fleet_p99_ttft_ms",
                "fleet_requests_ok", "router_failovers",
                "router_replays", "fleet_failover_parity",
                "kv_migration_ok", "kv_migration_adopted",
                "kv_migration_bytes_saved_pct",
                "kv_migration_fallbacks"):
        assert key in last, f"bench row missing {key!r}"
    assert last["fleet_tokens_per_sec"] > 0, last
    assert last["fleet_p99_ttft_ms"] > 0, last
    assert last["fleet_requests_ok"] > 0, last
    assert last["router_failovers"] >= 1, last
    assert last["router_replays"] >= 1, last
    assert last["fleet_failover_parity"] is True, last
    assert last["kv_migration_ok"] is True, last
    assert last["kv_migration_adopted"] >= 1, last
    assert last["kv_migration_bytes_saved_pct"] > 50.0, last
    assert last["kv_migration_fallbacks"] >= 1, last
    # MULTICHIP probe contract: the DP×TP static-executor step (forced
    # 8-device CPU topology in a subprocess) matches the single-chip
    # loss within the established gm tolerance, the row-parallel hint
    # really produced psum accounting, and the gradient-merge×pipeline
    # composition reports its GPipe stage count + analytic bubble (CPU
    # rows stay comparable: false — the fields are the contract, the
    # tokens/s are movement-only)
    for key in ("shard_tokens_per_sec", "shard_parity_delta",
                "shard_psums_inserted", "pp_bubble_frac", "pp_stages",
                "shard_vars_annotated"):
        assert key in last, f"bench row missing {key!r}"
    assert last["shard_tokens_per_sec"] > 0, last
    assert last["shard_parity_delta"] <= 1.2e-7, last
    assert last["shard_psums_inserted"] >= 1, last
    assert last["shard_vars_annotated"] > 0, last
    assert last["pp_stages"] == 2, last
    assert 0.0 < last["pp_bubble_frac"] < 1.0, last
    # quantized-collective contract (ISSUE 15): the int8 bucketed DP
    # all-reduce must save >= 60% of the f32 ring bytes while holding
    # the loss inside the established amp-style gate, with the buckets
    # emitted in completion order (overlap fraction (nb-1)/nb)
    for key in ("quant_allreduce_tokens_per_sec", "quant_loss_delta",
                "comm_bytes_saved_pct", "allreduce_overlap_frac",
                "comm_buckets"):
        assert key in last, f"bench row missing {key!r}"
    assert last["quant_allreduce_tokens_per_sec"] > 0, last
    assert last["quant_loss_delta"] <= 1e-2, last
    assert last["comm_bytes_saved_pct"] >= 60.0, last
    assert last["comm_buckets"] >= 2, last
    assert 0.0 < last["allreduce_overlap_frac"] < 1.0, last
    # pipeline-schedule + ZeRO contract (ISSUE 18): 1F1B's modeled
    # bubble beats gpipe's at the same (S, M); ZeRO-2 over dp=8 engages
    # (counted zero dispatch), collapses >= 40% of the per-device
    # optimizer-state bytes, and holds the loss inside the quant gate
    # vs the replicated comm leg
    for key in ("pp_1f1b_tokens_per_sec", "pp_1f1b_bubble_frac",
                "zero_stage", "zero_state_bytes_saved_pct",
                "zero_loss_delta", "zero_dispatches"):
        assert key in last, f"bench row missing {key!r}"
    assert last["pp_1f1b_tokens_per_sec"] > 0, last
    assert 0.0 < last["pp_1f1b_bubble_frac"] < last["pp_bubble_frac"], \
        last
    assert last["zero_stage"] == 2, last
    assert last["zero_state_bytes_saved_pct"] >= 40.0, last
    assert last["zero_loss_delta"] <= 1e-2, last
    assert last["zero_dispatches"] >= 1, last
    # kernel MFU push contract (ISSUE 19): the fused Pallas optimizer
    # engages on the ZeRO int8 leg (interpret-forced on CPU) and stays
    # inside the quant gate vs its PADDLE_FUSED_OPT=0 XLA twin; the
    # MoE probe's explicit all_to_all path is parity-gated vs the dense
    # oracle with its wire bytes charged in the cost model
    for key in ("fused_opt_step_ms", "fused_opt_xla_step_ms",
                "fused_opt_dispatches", "fused_opt_loss_delta",
                "fused_opt_note", "moe_tokens_per_sec",
                "moe_parity_delta", "moe_int8_loss_delta",
                "moe_capacity_drop_pct", "moe_a2a_dispatches",
                "moe_a2a_bytes", "moe_a2a_bytes_saved_pct"):
        assert key in last, f"bench row missing {key!r}"
    assert last["fused_opt_step_ms"] > 0, last
    assert last["fused_opt_dispatches"] >= 1, last
    assert last["fused_opt_loss_delta"] <= 1e-2, last
    assert last["moe_tokens_per_sec"] > 0, last
    assert last["moe_parity_delta"] <= 1e-5, last
    assert last["moe_int8_loss_delta"] <= 1e-2, last
    assert last["moe_a2a_dispatches"] >= 1, last
    assert last["moe_a2a_bytes"] > 0, last
    assert last["moe_a2a_bytes_saved_pct"] > 0.0, last


@pytest.mark.slow
def test_bench_default_invocation_with_dead_tunnel(tmp_path):
    """The exact driver invocation (no env overrides): placeholder row in
    <60 s, smoke-measured headline row last, rc 0 — un-timeout-able."""
    env = _dead_tunnel_env(tmp_path)
    # generous deadlines: this runs in the slow tier, often concurrent
    # with model-training tests saturating the box
    rc, lines, first = _run_streaming(
        [sys.executable, BENCH], env,
        first_row_deadline=120, total_deadline=600)
    assert rc == 0
    rows = [json.loads(ln) for ln in lines if ln.startswith("{")]
    assert rows[0].get("placeholder") is True
    last = rows[-1]
    assert last["metric"] == "bert_base_pretrain_tokens_per_sec_per_chip"
    assert last.get("placeholder") is None  # real smoke measurement
    assert last["value"] > 0, last
    assert last.get("degraded") is True


@pytest.mark.parametrize(
    "delay", [3, pytest.param(15, marks=pytest.mark.slow)])
def test_bench_sigterm_still_emits_row(tmp_path, delay):
    """An external `timeout`-style SIGTERM still yields a parseable
    final row and rc 0 (the rc=124 class is closed) — both during the
    probe window (delay 3 < probe timeout 5) and mid-measurement."""
    env = _dead_tunnel_env(tmp_path)
    proc = subprocess.Popen([sys.executable, BENCH], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    # the delay clock starts when the handler is armed, not at exec: on a
    # loaded machine interpreter startup (sitecustomize imports jax) can
    # eat seconds, and a TERM before the handler gets default disposition.
    # A reader thread keeps the wait bounded even if stderr goes silent.
    armed = threading.Event()

    def _wait_armed():
        for line in proc.stderr:
            if "signal net armed" in line:
                armed.set()
                return

    th = threading.Thread(target=_wait_armed, daemon=True)
    th.start()
    armed.wait(timeout=60)
    time.sleep(delay)
    proc.terminate()
    try:
        out, _ = proc.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("bench.py did not exit after SIGTERM")
    assert proc.returncode == 0
    rows = [json.loads(ln) for ln in out.strip().splitlines()
            if ln.startswith("{")]
    assert rows, out
    assert all("metric" in r for r in rows)


def test_probe_cache_skips_repeat_timeout(tmp_path):
    """Second probe against a dead tunnel reads the cached failure
    verdict instead of re-paying the subprocess timeout."""
    env = _dead_tunnel_env(tmp_path, PADDLE_TPU_PROBE_TIMEOUT="4")
    src = ("import time, paddle_tpu.framework.bringup as b;"
           "t0=time.monotonic();"
           "p=b.probe_backend();"
           "print('P1', p, round(time.monotonic()-t0, 2))")
    def _probe_secs(out):
        # The subprocess prints its own in-process elapsed ("P1 None 4.0"),
        # which excludes interpreter startup — wall-clock around the
        # subprocess is load-sensitive (importing jax under a saturated
        # machine can alone exceed the probe timeout).
        return float(out.stdout.split()[-1])

    out1 = subprocess.run([sys.executable, "-c", src], env=env,
                          capture_output=True, text=True, timeout=180)
    assert "P1 None" in out1.stdout, (out1.stdout, out1.stderr)
    dt1 = _probe_secs(out1)
    assert dt1 > 3, "first probe should pay the timeout"
    out2 = subprocess.run([sys.executable, "-c", src], env=env,
                          capture_output=True, text=True, timeout=180)
    assert "P1 None" in out2.stdout
    dt2 = _probe_secs(out2)
    assert dt2 < dt1, (dt1, dt2)
    assert dt2 < 2, f"cached probe verdict should be instant, took {dt2}"


def test_library_first_touch_degrades_not_hangs(tmp_path):
    """VERDICT r2 weak #4: plain `import paddle_tpu; to_tensor(...)` with
    a dead tunnel must fall back to cpu, not block forever."""
    env = _dead_tunnel_env(tmp_path)
    src = (
        "import numpy as np, paddle_tpu as paddle\n"
        "t = paddle.to_tensor(np.ones((2, 2), np.float32))\n"
        "print('PLATFORM', t.value.devices().pop().platform)\n")
    out = subprocess.run([sys.executable, "-c", src], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "PLATFORM cpu" in out.stdout


@pytest.mark.slow
def test_eager_train_step_degrades_not_hangs(tmp_path):
    """Eager LeNet step end-to-end on the degraded backend."""
    env = _dead_tunnel_env(tmp_path)
    src = (
        "import numpy as np, paddle_tpu as paddle\n"
        "from paddle_tpu import nn, optimizer\n"
        "from paddle_tpu.vision.models import LeNet\n"
        "m = LeNet(num_classes=10)\n"
        "opt = optimizer.Adam(learning_rate=1e-3,"
        " parameters=m.parameters())\n"
        "ce = nn.CrossEntropyLoss()\n"
        "x = paddle.to_tensor(np.zeros((2, 1, 28, 28), np.float32))\n"
        "y = paddle.to_tensor(np.zeros((2,), np.int64))\n"
        "loss = ce(m(x), y); loss.backward(); opt.step()\n"
        "print('LOSS', float(loss))\n")
    out = subprocess.run([sys.executable, "-c", src], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "LOSS" in out.stdout
