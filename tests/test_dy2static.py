"""dygraph-to-static AST engine (paddle_tpu/dy2static.py — reference
dygraph_to_static/ ifelse/loop/logical transformers + convert_operators):
tensor-dependent Python control flow must compile under jit via
lax.cond/lax.while_loop, while concrete values keep Python semantics."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import dy2static, nn
from paddle_tpu.jit import to_static


pytestmark = pytest.mark.slow

def t(x, dtype=np.float32):
    return paddle.to_tensor(np.asarray(x, dtype))


def test_convert_ifelse_concrete_and_python():
    out = dy2static.convert_ifelse(True, lambda: (1,), lambda: (2,))
    assert out == (1,)
    out = dy2static.convert_ifelse(t(0.0) > 1.0, lambda: (t(1.0),),
                                   lambda: (t(2.0),))
    assert float(out[0].numpy()) == 2.0


def test_if_on_tensor_under_jit():
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    pos = f(t([1.0, 2.0]))
    neg = f(t([-1.0, -2.0]))
    np.testing.assert_allclose(pos.numpy(), [2.0, 4.0])
    np.testing.assert_allclose(neg.numpy(), [-2.0, -3.0])


def test_if_else_missing_branch_var_errors():
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        else:
            z = x - 1.0          # y undefined on this path
        return y

    # one-sided names become UNDEF; the clear error surfaces at USE
    with pytest.raises(NameError, match="branch"):
        f(t([1.0]))


def test_while_on_tensor_under_jit():
    @to_static
    def f(x):
        s = x * 0.0
        i = t(0.0)
        while (i < 5.0):
            s = s + x
            i = i + 1.0
        return s

    out = f(t([2.0, 3.0]))
    np.testing.assert_allclose(out.numpy(), [10.0, 15.0])


def test_while_data_dependent_trip_count():
    """Test depends on the traced input -> lowers to lax.while_loop
    (forward-only: jax while_loop is not reverse-differentiable)."""
    @to_static
    def f(x):
        while (x.sum() < 100.0):
            x = x * 2.0
        return x

    out = f(t([1.0, 2.0]))          # 3 -> 6 -> ... -> 192
    np.testing.assert_allclose(out.numpy(), [64.0, 128.0])
    out = f(t([200.0, 0.0]))        # never enters
    np.testing.assert_allclose(out.numpy(), [200.0, 0.0])


def test_while_with_temporary_local():
    @to_static
    def f(x):
        i = t(0.0)
        acc = x * 0.0
        while (i < 3.0):
            delta = x + i        # per-iteration temporary, UNDEF at entry
            acc = acc + delta
            i = i + 1.0
        return acc

    out = f(t([1.0]))
    np.testing.assert_allclose(out.numpy(), [6.0])  # (1+0)+(1+1)+(1+2)


def test_for_range_python_and_nested_if():
    @to_static
    def f(x):
        acc = x * 0.0
        for k in range(4):
            if (x.sum() > 0.0):
                acc = acc + x
            else:
                acc = acc - x
        return acc

    np.testing.assert_allclose(f(t([1.0])).numpy(), [4.0])
    np.testing.assert_allclose(f(t([-1.0])).numpy(), [4.0])


def test_logical_ops():
    @to_static
    def f(x, y):
        both = (x.sum() > 0.0) and (y.sum() > 0.0)
        either = (x.sum() > 0.0) or (y.sum() > 0.0)
        neither = not either
        if both:
            out = x + y
        else:
            out = x - y
        return out, either, neither

    out, either, neither = f(t([1.0]), t([2.0]))
    np.testing.assert_allclose(out.numpy(), [3.0])
    assert bool(either.numpy()) and not bool(neither.numpy())
    out, either, neither = f(t([-1.0]), t([-2.0]))
    np.testing.assert_allclose(out.numpy(), [1.0])
    assert not bool(either.numpy()) and bool(neither.numpy())


def test_mixed_and_or_value_semantics():
    """Python operand-selection semantics for concrete values: `x or 5.0`
    returns x when truthy; `x and 7.0` returns 7.0 (review regression)."""
    x = t(3.0)
    assert dy2static.convert_logical_or(lambda: x, lambda: 5.0) is x
    assert dy2static.convert_logical_and(lambda: x, lambda: 7.0) == 7.0
    zero = t(0.0)
    assert dy2static.convert_logical_or(lambda: zero, lambda: 5.0) == 5.0
    assert dy2static.convert_logical_and(lambda: zero, lambda: 7.0) is zero
    # the `scale = scale or default` idiom survives transformation
    def f(x, scale):
        scale = scale or 2.0
        return x * scale

    fc = dy2static.ast_transform(f)
    np.testing.assert_allclose(fc(t([3.0]), None).numpy(), [6.0])


def test_for_range_target_shadows_bound():
    """`for n in range(n)` must read the OLD n for its bound (review
    regression: desugar used to clobber the bound first)."""
    def h(n):
        tot = 0
        for n in range(n):
            tot = tot + n
        return tot

    hc = dy2static.ast_transform(h)
    assert hc(4) == 6


def test_to_static_transform_is_memoized():
    def f(x):
        if (x.sum() > 0.0):
            y = x
        else:
            y = -x
        return y

    a = to_static(f)
    b = to_static(f)
    from paddle_tpu.jit import _ast_cache
    assert f in _ast_cache
    assert a(t([2.0])).numpy() == b(t([2.0])).numpy()


def test_python_short_circuit_preserved():
    calls = []

    def right():
        calls.append(1)
        return True

    assert dy2static.convert_logical_and(lambda: False, right) is False
    assert calls == []   # rhs never evaluated for Python lhs


def test_eager_path_keeps_tape_gradients():
    """Outside jit the converters take the Python branch, so the eager
    tape still sees every op."""
    def f(x):
        if (x.sum() > 0.0):
            return (x * 3.0).sum()
        return (x * 5.0).sum()

    fc = dy2static.ast_transform(f)
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    loss = fc(x)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0])


def test_gradient_through_cond_and_while():
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep

    class Gated(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if (h.sum() > 0.0):
                out = h * 2.0
            else:
                out = h * 0.5
            i = t(0.0)
            while (i < 2.0):
                out = out + h
                i = i + 1.0
            return out

    paddle.seed(0)
    model = Gated()
    model.forward = dy2static.ast_transform(
        type(model).forward).__get__(model)
    opt = optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
    step = TrainStep(model, lambda m, x: (m(x) ** 2).mean(), opt)
    x = t(np.random.RandomState(0).randn(2, 4))
    # the while trip count is tensor-dependent under trace; the bounded
    # scan form makes it reverse-differentiable
    with dy2static.max_loop_iters(4):
        l0 = float(step(x))
        for _ in range(5):
            l1 = float(step(x))
    assert l1 < l0


def test_program_translator_toggle():
    dy2static.ProgramTranslator().enable(False)
    try:
        def f(x):
            if (x.sum() > 0.0):
                y = x
            else:
                y = -x
            return y

        g = to_static(f)
        # trace-only mode: tensor-dependent if raises jax's tracer error
        with pytest.raises(Exception):
            g(t([1.0]))
    finally:
        dy2static.ProgramTranslator().enable(True)
    assert dy2static.ast_enabled()


def test_elif_chain_on_tensor():
    """elif chains (nested ifs) must not leak synthetic helper names into
    the outer branch variable set (review regression)."""
    def h(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        elif (x.sum() < -10.0):
            y = x * 3.0
        else:
            y = x - 1.0
        return y

    hc = to_static(h)
    np.testing.assert_allclose(hc(t([1.0])).numpy(), [2.0])
    np.testing.assert_allclose(hc(t([-20.0])).numpy(), [-60.0])
    np.testing.assert_allclose(hc(t([-1.0])).numpy(), [-2.0])


def test_for_range_last_value_semantics():
    """After the loop the target holds the last YIELDED value, not the
    bound (review regression)."""
    def f():
        tot = 0
        for k in range(3):
            tot = tot + k
        return k, tot

    fc = dy2static.ast_transform(f)
    assert fc() == (2, 3)


def test_inner_break_does_not_block_outer_while():
    """A break inside an inner Python for must not stop the enclosing
    tensor-dependent while from converting (review regression)."""
    @to_static
    def f(x):
        while (x.sum() < 10.0):
            for j in range(5):
                if j == 2:
                    break
                x = x + 1.0
        return x

    out = f(t([0.0]))        # +2 per outer iteration until >= 10
    assert float(out.numpy()[0]) >= 10.0


def test_early_return_in_tensor_if():
    """Tail returns inside if branches are lifted to assignments
    (reference return_transformer.py), so tensor predicates work with
    early-return style."""
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            return x * 2.0
        return x - 1.0

    np.testing.assert_allclose(f(t([3.0])).numpy(), [6.0])
    np.testing.assert_allclose(f(t([-3.0])).numpy(), [-4.0])


def test_early_return_chain_and_trailing_code():
    @to_static
    def f(x):
        if (x.sum() > 10.0):
            return x * 10.0
        y = x + 1.0
        if (y.sum() > 0.0):
            return y
        return -y

    np.testing.assert_allclose(f(t([20.0])).numpy(), [200.0])
    np.testing.assert_allclose(f(t([1.0])).numpy(), [2.0])
    np.testing.assert_allclose(f(t([-5.0])).numpy(), [4.0])


def test_early_return_implicit_none():
    def g(x):
        if x > 10:
            return "big"

    gc = dy2static.ast_transform(g)
    assert gc(20) == "big" and gc(1) is None


def test_else_only_tail_return():
    """else-branch tail returns are lifted too (review regression)."""
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        else:
            return x - 1.0
        return y + 1.0

    np.testing.assert_allclose(f(t([3.0])).numpy(), [7.0])
    np.testing.assert_allclose(f(t([-3.0])).numpy(), [-4.0])

    def g(n):
        if n > 0:
            y = n
        else:
            return -1
        return y * 10

    gc = dy2static.ast_transform(g)
    assert gc(3) == 30 and gc(-3) == -1

    def h(n):              # else-return at function end, body falls off
        if n > 0:
            y = n
        else:
            return -1

    hc = dy2static.ast_transform(h)
    assert hc(3) is None and hc(-3) == -1


def test_for_over_tensor_rows():
    """`for row in tensor` iterates the leading dim (Tensor.__iter__):
    unrolls at trace time; per-row tensor-dependent ifs convert to
    lax.cond inside the unrolled body."""
    @to_static
    def f(x):
        acc = x[0] * 0.0
        for row in x:
            if (row.sum() > 100.0):
                acc = acc - row      # outlier rows are subtracted
            else:
                acc = acc + row
        return acc

    out = f(t([[1.0, 2.0], [3.0, 4.0], [1000.0, 0.0], [5.0, 6.0]]))
    np.testing.assert_allclose(out.numpy(), [-991.0, 12.0])


def test_nested_and_elif_return_python_semantics():
    """Review regressions: end-of-branch is NOT end-of-function — nested
    ifs and elif chains with trailing code keep Python semantics."""
    def f(a, b):
        if a:
            if b:
                return 1
        return 2

    fc = dy2static.ast_transform(f)
    assert fc(True, True) == 1
    assert fc(True, False) == 2
    assert fc(False, False) == 2

    def g(a, b):
        if a:
            return 1
        elif b:
            return 2
        return 3

    gc = dy2static.ast_transform(g)
    assert gc(True, False) == 1
    assert gc(False, True) == 2
    assert gc(False, False) == 3


def test_undef_equality_raises():
    with pytest.raises(NameError, match="undefined"):
        dy2static.UNDEF == 1
    with pytest.raises(NameError, match="undefined"):
        dy2static.UNDEF != 1
    with pytest.raises(AttributeError, match="undefined"):
        dy2static.UNDEF.shape


def test_tensor_if_return_vs_fallthrough_clear_error():
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            return x * 2.0
        # falls through -> returns None

    with pytest.raises(NameError, match="branch"):
        f(t([1.0]))


def test_one_sided_none_assignment_is_undef_not_error():
    """Assigning None on one branch is a branch-local binding, not a
    return mismatch (review regression)."""
    @to_static
    def f(x):
        if (x.sum() > 0.0):
            y = None            # never used afterwards
        out = x * 3.0
        return out

    np.testing.assert_allclose(f(t([2.0])).numpy(), [6.0])


def test_undef_attribute_protocol():
    import copy
    assert not hasattr(dy2static.UNDEF, "shape")
    assert getattr(dy2static.UNDEF, "numpy", None) is None
    copy.deepcopy({"a": dy2static.UNDEF})   # must not raise
