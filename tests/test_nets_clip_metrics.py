"""fluid.nets compositions, static gradient clipping, extra initializers,
and fluid.metrics classes (reference nets.py / clip.py / initializer.py /
metrics.py)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu import metric


def test_simple_img_conv_pool_and_glu():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("img", [-1, 1, 28, 28])
        conv_pool = static.nets.simple_img_conv_pool(
            img, num_filters=8, filter_size=5, pool_size=2, pool_stride=2,
            act="relu")
        flat = static.flatten(conv_pool, axis=1)
        gated = static.nets.glu(flat, dim=-1)
    exe = static.Executor()
    exe.run(startup)
    out, g = exe.run(main, feed={"img": np.random.RandomState(0).rand(
        2, 1, 28, 28).astype(np.float32)}, fetch_list=[conv_pool, gated])
    assert np.asarray(out).shape == (2, 8, 12, 12)
    assert np.asarray(g).shape[-1] == np.asarray(out).reshape(2, -1).shape[-1] // 2


def test_img_conv_group_vgg_block():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("img", [-1, 3, 16, 16])
        out = static.nets.img_conv_group(
            img, conv_num_filter=[8, 8], pool_size=2, conv_act="relu",
            conv_with_batchnorm=True, conv_batchnorm_drop_rate=0.1,
            pool_stride=2)
    exe = static.Executor()
    exe.run(startup)
    o, = exe.run(main, feed={"img": np.random.RandomState(1).rand(
        2, 3, 16, 16).astype(np.float32)}, fetch_list=[out])
    assert np.asarray(o).shape == (2, 8, 8, 8)


def test_sequence_conv_pool_masked():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        seq = static.data("seq", [-1, 6, 4])
        mask = static.data("mask", [-1, 6, 1])
        pooled = static.nets.sequence_conv_pool(
            seq, num_filters=5, filter_size=3, act="relu",
            pool_type="max", mask=mask)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(2)
    x = rng.rand(3, 6, 4).astype(np.float32)
    m = np.ones((3, 6, 1), np.float32)
    m[:, 4:] = 0.0
    o, = exe.run(main, feed={"seq": x, "mask": m}, fetch_list=[pooled])
    o = np.asarray(o)
    assert o.shape == (3, 5)
    # masked steps must not win the max-pool: recompute with zeroed tail
    x2 = x.copy()
    x2[:, 4:] = 100.0          # huge values on masked steps
    o2, = exe.run(main, feed={"seq": x2, "mask": m}, fetch_list=[pooled])
    np.testing.assert_allclose(np.asarray(o2), o, rtol=1e-5)


def test_scaled_dot_product_attention_net():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        q = static.data("q", [-1, 4, 8])
        k = static.data("k", [-1, 6, 8])
        v = static.data("v", [-1, 6, 8])
        ctx = static.nets.scaled_dot_product_attention(q, k, v, num_heads=2)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(3)
    o, = exe.run(main, feed={
        "q": rng.rand(2, 4, 8).astype(np.float32),
        "k": rng.rand(2, 6, 8).astype(np.float32),
        "v": rng.rand(2, 6, 8).astype(np.float32)}, fetch_list=[ctx])
    assert np.asarray(o).shape == (2, 4, 8)


@pytest.mark.parametrize("clip", [
    static.GradientClipByValue(0.01),
    static.GradientClipByNorm(0.05),
    static.GradientClipByGlobalNorm(0.05),
])
def test_static_gradient_clip(clip):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 4])
        y = static.data("y", [-1, 1])
        pred = static.nn.fc(x, 1)
        loss = static.mean(static.square_error_cost(pred, y))
        static.SGD(learning_rate=1.0, grad_clip=clip).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    # huge targets -> huge raw grads; the clip keeps params from exploding
    feed = {"x": rng.rand(8, 4).astype(np.float32),
            "y": (rng.rand(8, 1) * 1e4).astype(np.float32)}
    scope = static.global_scope()
    w_name = main.all_parameters()[0].name
    w0 = np.asarray(scope.find_var(w_name))
    exe.run(main, feed=feed, fetch_list=[loss])
    w1 = np.asarray(static.global_scope().find_var(w_name))
    assert np.abs(w1 - w0).max() < 1.0, np.abs(w1 - w0).max()


def test_set_gradient_clip_default():
    static.set_gradient_clip(static.GradientClipByValue(0.001))
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 4])
            y = static.data("y", [-1, 1])
            loss = static.mean(
                static.square_error_cost(static.nn.fc(x, 1), y))
            static.SGD(learning_rate=1.0).minimize(loss)
        assert any(op.type == "clip" for op in main.global_block.ops)
    finally:
        static.set_gradient_clip(None)


def test_numpy_array_and_bilinear_initializers():
    from paddle_tpu.static.initializer import (Bilinear,
                                               NumpyArrayInitializer)
    val = np.arange(12, dtype=np.float32).reshape(3, 4)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        w = static.create_parameter(
            [3, 4], "float32", name="w_np",
            default_initializer=NumpyArrayInitializer(val))
        up = static.create_parameter(
            [2, 2, 4, 4], "float32", name="w_bl",
            default_initializer=Bilinear())
    exe = static.Executor()
    exe.run(startup)
    scope = static.global_scope()
    np.testing.assert_allclose(np.asarray(scope.find_var("w_np")), val)
    blw = np.asarray(scope.find_var("w_bl"))
    assert blw.shape == (2, 2, 4, 4)
    assert blw.max() <= 1.0 and blw[0, 0].sum() > 0


def test_set_global_initializer():
    from paddle_tpu.static import initializer as I
    I.set_global_initializer(I.Constant(0.5))
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            static.nn.fc(static.data("x", [-1, 4]), 3, bias_attr=False)
        exe = static.Executor()
        exe.run(startup)
        w = np.asarray(static.global_scope().find_var(
            main.all_parameters()[0].name))
        np.testing.assert_allclose(w, 0.5)
    finally:
        I.set_global_initializer(None)


def test_composite_and_chunk_and_edit_distance_metrics():
    comp = metric.CompositeMetric()
    acc = metric.Accuracy()
    comp.add_metric(acc)
    comp.reset()

    chunk = metric.ChunkEvaluator()
    chunk.update(10, 8, 6)
    p, r, f1 = chunk.accumulate()
    assert abs(p - 0.6) < 1e-9 and abs(r - 0.75) < 1e-9
    assert abs(f1 - 2 * p * r / (p + r)) < 1e-9

    ed = metric.EditDistance()
    ed.update(np.array([0.0, 2.0, 1.0]))
    avg, err = ed.accumulate()
    assert abs(avg - 1.0) < 1e-9 and abs(err - 2 / 3) < 1e-9


def test_detection_map():
    m = metric.DetectionMAP(overlap_threshold=0.5)
    gts = np.array([[0, 0, 0, 10, 10], [1, 20, 20, 30, 30]], np.float32)
    # one perfect match per class, one false positive
    preds = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [1, 0.8, 20, 20, 30, 30],
        [0, 0.7, 50, 50, 60, 60],
    ], np.float32)
    m.update(preds, gts)
    assert abs(m.accumulate() - 1.0) < 1e-9   # FP after full recall
    m2 = metric.DetectionMAP()
    m2.update(np.array([[0, 0.9, 50, 50, 60, 60]], np.float32),
              np.array([[0, 0, 0, 10, 10]], np.float32))
    assert m2.accumulate() == 0.0


def test_set_global_initializer_bias_slot():
    """The bias_init argument must reach bias parameters (review
    regression: it was stored but never read)."""
    from paddle_tpu.static import initializer as I
    I.set_global_initializer(I.Constant(0.25), I.Constant(1.5))
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            static.nn.fc(static.data("x", [-1, 4]), 3)
        exe = static.Executor()
        exe.run(startup)
        scope = static.global_scope()
        vals = sorted(
            float(np.asarray(scope.find_var(p.name)).reshape(-1)[0])
            for p in main.all_parameters())
        assert vals == [0.25, 1.5], vals
    finally:
        I.set_global_initializer(None, None)


def test_detection_map_difficult_boxes():
    gts = np.array([[0, 0, 0, 10, 10, 0], [0, 20, 20, 30, 30, 1]],
                   np.float32)          # second box difficult
    preds = np.array([[0, 0.9, 0, 0, 10, 10],
                      [0, 0.8, 20, 20, 30, 30]], np.float32)
    m = metric.DetectionMAP(evaluate_difficult=False)
    m.update(preds, gts)
    # difficult GT excluded from denominator; its matched pred ignored
    assert abs(m.accumulate() - 1.0) < 1e-9
    m2 = metric.DetectionMAP(evaluate_difficult=True)
    m2.update(preds, gts)
    assert abs(m2.accumulate() - 1.0) < 1e-9
