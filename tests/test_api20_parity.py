"""2.0-alpha API surface parity (reference python/paddle/{nn,tensor,
optimizer} at v1.8): pre-rename spellings resolve, the namespaces close
to zero missing names, and the genuinely-new layers compute correctly."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.compat20 as c20


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def test_reference_nn_all_resolves():
    missing = [n for n in c20._REFERENCE_NN_ALL if not hasattr(nn, n)]
    assert not missing, missing


def test_optimizer_aliases():
    import paddle_tpu.optimizer as opt
    assert opt.SGDOptimizer is opt.SGD
    assert opt.MomentumOptimizer is opt.Momentum
    assert opt.ExponentialMovingAverage is opt.EMA
    assert opt.StepLR is opt.lr.StepDecay
    assert opt._LRScheduler is opt.lr.LRScheduler
    assert callable(opt.PipelineOptimizer)


def test_tensor_namespace():
    import paddle_tpu.tensor as T
    r = T.reduce_mean(np.asarray([[1.0, 3.0]]), dim=1)
    np.testing.assert_allclose(_np(r), [2.0])
    assert int(_np(T.numel(np.ones((2, 5))))) == 10
    out = T.elementwise_sum([np.ones(3), np.ones(3), np.ones(3)])
    np.testing.assert_allclose(_np(out), 3.0)
    fd = T.elementwise_floordiv(np.asarray([7]), np.asarray([2]))
    assert int(_np(fd)[0]) == 3


def test_lowercase_class_aliases_construct():
    conv = nn.Conv2d(3, 8, 3)          # pre-rename spelling
    x = paddle.to_tensor(np.random.randn(1, 3, 8, 8).astype(np.float32))
    y = conv(x)
    assert tuple(y.shape) == (1, 8, 6, 6)
    pool = nn.MaxPool2d(2)
    assert tuple(pool(y).shape) == (1, 8, 3, 3)
    pad = nn.ZeroPad2d([1, 1, 1, 1])
    assert tuple(pad(y).shape) == (1, 8, 8, 8)
    rp = nn.ReplicationPad2d([1, 1, 1, 1])
    assert tuple(rp(y).shape) == (1, 8, 8, 8)


def test_bilinear_tensor_product():
    layer = nn.BilinearTensorProduct(3, 4, 5)
    x1 = paddle.to_tensor(np.random.randn(2, 3).astype(np.float32))
    x2 = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    y = layer(x1, x2)
    assert tuple(y.shape) == (2, 5)
    # closed form check against einsum
    w = _np(layer.weight)
    b = _np(layer.bias)
    exp = np.einsum("bi,kij,bj->bk", _np(x1), w, _np(x2)) + b
    np.testing.assert_allclose(_np(y), exp, rtol=1e-5)


def test_pairwise_distance():
    pd = nn.PairwiseDistance(p=2.0)
    x = np.asarray([[0.0, 0.0], [1.0, 1.0]], np.float32)
    y = np.asarray([[3.0, 4.0], [1.0, 1.0]], np.float32)
    d = _np(pd(paddle.to_tensor(x), paddle.to_tensor(y)))
    np.testing.assert_allclose(d, [5.0, np.sqrt(2) * 1e-6], atol=1e-4)


def test_row_conv_lookahead():
    rc = nn.RowConv(4, future_context_size=2)
    x = paddle.to_tensor(np.random.randn(2, 6, 4).astype(np.float32))
    y = rc(x)
    assert tuple(y.shape) == (2, 6, 4)
    # the last timestep only sees itself (zero future padding)
    w = _np(rc.weight)
    exp_last = _np(x)[:, -1] * w[0]
    np.testing.assert_allclose(_np(y)[:, -1], exp_last, rtol=1e-5)


def test_hsigmoid_loss_decreases_under_training():
    num_classes, dim, b = 8, 16, 32
    rng = np.random.RandomState(0)
    head = nn.HSigmoid(dim, num_classes)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=list(head.parameters()))
    x = paddle.to_tensor(rng.randn(b, dim).astype(np.float32))
    label = paddle.to_tensor(rng.randint(0, num_classes, b))
    first = None
    for _ in range(25):
        loss = head(x, label).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.value)
    assert float(loss.value) < first * 0.5, (first, float(loss.value))


def test_pool2d_facade():
    p = nn.Pool2D(pool_size=2, pool_type="avg", pool_stride=2)
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y = _np(p(x))
    np.testing.assert_allclose(y[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    g = nn.Pool2D(pool_type="max", global_pooling=True)
    assert float(_np(g(x)).reshape(())) == 15.0


def test_instance_norm_rank_dispatch():
    innorm = nn.InstanceNorm(4)
    for shape in [(2, 4, 8), (2, 4, 8, 8)]:
        x = paddle.to_tensor(np.random.randn(*shape).astype(np.float32))
        y = _np(innorm(x))
        assert y.shape == shape
        # per-instance-channel normalization: mean ~ 0
        assert abs(y.reshape(2, 4, -1).mean(-1)).max() < 1e-4


def test_weight_norm_reparametrization():
    lin = nn.Linear(4, 3)
    w0 = _np(lin.weight).copy()
    nn.weight_norm(lin, "weight", dim=0)
    assert hasattr(lin, "weight_g") and hasattr(lin, "weight_v")
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    y1 = _np(lin(x))
    # effective weight reproduces the original at init
    np.testing.assert_allclose(_np(lin.weight), w0, rtol=1e-5, atol=1e-6)
    nn.remove_weight_norm(lin, "weight")
    assert not hasattr(lin, "_weight_norm_hook")
    y2 = _np(lin(x))
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-6)


def test_remove_weight_norm_weight_is_trainable_again():
    lin = nn.Linear(4, 3)
    nn.weight_norm(lin, "weight")
    nn.remove_weight_norm(lin, "weight")
    # the restored weight must be the parameter forward actually reads
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    y1 = _np(lin(x))
    lin.weight.set_value(np.zeros_like(_np(lin.weight)))
    y2 = _np(lin(x))
    assert not np.allclose(y1, y2) or np.allclose(y1, _np(lin.bias))


def test_instance_norm_registers_parameters():
    innorm = nn.InstanceNorm(4)
    assert len(list(innorm.parameters())) >= 2
    assert innorm.state_dict()


def test_mul_restores_reference_shape():
    import paddle_tpu.tensor as T
    out = T.mul(np.ones((2, 3, 4), np.float32),
                np.ones((4, 5), np.float32), x_num_col_dims=2)
    assert _np(out).shape == (2, 3, 5)


def test_logsigmoid():
    x = np.asarray([-2.0, 0.0, 3.0], np.float32)
    out = _np(nn.logsigmoid(x))
    np.testing.assert_allclose(out, np.log(1 / (1 + np.exp(-x))),
                               rtol=1e-5)
