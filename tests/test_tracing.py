"""Distributed request tracing + metrics federation + SLO burn-rate
plane (PR 14):

- span primitives: linkage, typed status, events, ambient context,
  in-flight table, deterministic timings under a fake clock;
- a decode request under continuous-batching load leaves a COMPLETE
  span tree in the JSONL (admission through per-tick decode to
  respond, preemption visible as a span event);
- serving engine request lifecycle spans with typed deadline status;
- cross-process propagation: a PS pull inside a traced region yields
  a server-side ps_rpc span linked to the caller's trace over the v2
  wire header — including across a chaos-drill failover to the
  promoted backup — and http_kv requests link via headers;
- federation: merge with instance labels, a killed endpoint mid-scrape
  degrades to staleness gauges (merged output still renders);
- SLO: burn rates from cumulative-bucket deltas over multi-window
  snapshots; tools/slo_check.py exits non-zero on a synthetic burn and
  zero on a healthy scrape;
- tools/trace_view.py renders trees/critical paths and refuses unknown
  schemas; the flight recorder postmortem names in-flight requests.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.observability import tracing
from paddle_tpu.observability.step_trace import (disable_step_trace,
                                                 enable_step_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spans(path):
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("kind") == "span":
                out.append(rec)
    return out


@pytest.fixture
def sink(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    enable_step_trace(path)
    yield path
    disable_step_trace()


# ---------------------------------------------------------------------------
# span primitives
# ---------------------------------------------------------------------------
def test_span_linkage_status_events_fake_clock(sink):
    clk = [0.0]

    def clock():
        clk[0] += 0.125
        return clk[0]

    root = tracing.Span("req", parent=False, clock=clock, root=True)
    root_hex = format(root.span_id, "016x")
    assert any(e["span"] == root_hex
               for e in tracing.inflight_snapshot())
    with root.activate():
        assert tracing.current_context().trace_id == root.trace_id
        with tracing.span("child", clock=clock) as c:
            c.event("preempted", slot=1)
    assert tracing.current_context() is None
    root.fail(ValueError("boom"))
    root.end()     # idempotent: first end wins
    # membership, not emptiness: earlier suite tests legitimately
    # strand requests (engine stop() leaves queued handles unresolved)
    assert all(e["span"] != root_hex
               for e in tracing.inflight_snapshot())
    recs = _spans(sink)
    child, parent = recs[0], recs[1]
    assert child["name"] == "child"
    assert child["trace"] == parent["trace"]
    assert child["parent"] == parent["span"]
    assert child["events"][0]["name"] == "preempted"
    # fake clock: exact durations (0.125 s per tick, ms in the record;
    # the child consumes two ticks: one for the event stamp, one at end)
    assert child["dur_ms"] == pytest.approx(250.0)
    assert child["events"][0]["t_ms"] == pytest.approx(125.0)
    assert parent["status"] == "ValueError"
    assert parent["dur_ms"] == pytest.approx(500.0)


def test_span_context_wire_and_headers_roundtrip():
    ctx = tracing.SpanContext(0x1234, 0x5678)
    assert tracing.SpanContext.from_wire(*ctx.to_wire()).span_id == 0x5678
    assert tracing.SpanContext.from_wire(0, 7) is None
    h = ctx.to_headers()
    back = tracing.SpanContext.from_headers(h)
    assert (back.trace_id, back.span_id) == (0x1234, 0x5678)
    assert tracing.SpanContext.from_headers({}) is None


# ---------------------------------------------------------------------------
# decode engine: the complete request tree
# ---------------------------------------------------------------------------
def _drive(eng, max_ticks=500):
    for _ in range(max_ticks):
        if not eng.sched.pending():
            return
        eng.run_once()
    raise AssertionError("engine did not drain the workload")


def test_decode_request_leaves_complete_span_tree(sink):
    from paddle_tpu.inference.decode import (DecodeEngine,
                                             DecodeModelConfig)

    cfg = DecodeModelConfig(vocab_size=32, n_layers=1, n_heads=2,
                            head_dim=8, ffn_dim=16, max_context=32)
    eng = DecodeEngine(cfg, seed=3, max_batch=2, n_pages=16, page_size=4,
                       max_pages_per_seq=8)
    eng.warm()
    hs = [eng.submit([1 + i, 2, 3], max_new_tokens=4) for i in range(3)]
    _drive(eng)
    for h in hs:
        h.result(timeout=5)
        assert len(h.stats()["trace_id"]) == 16
    recs = _spans(sink)
    by_trace = {}
    for r in recs:
        by_trace.setdefault(r["trace"], []).append(r)
    for h in hs:
        tid = h.stats()["trace_id"]
        tree = by_trace[tid]
        names = [r["name"] for r in tree]
        # admission -> queue wait -> prefill -> respond, all linked
        assert names.count("decode.request") == 1
        assert "decode.queue" in names and "decode.prefill" in names
        root = next(r for r in tree if r["name"] == "decode.request")
        assert root["status"] == "ok"
        assert root["parent"] is None
        assert root["attrs"]["tokens"] == 4
        for r in tree:
            if r is not root:
                assert r["parent"] == root["span"], r
        # per-tick decode spans reference this request by trace id
        ticks = [r for r in recs if r["name"] == "decode.tick"
                 and tid in (r.get("attrs", {}).get("requests") or ())]
        assert ticks, f"no tick span names trace {tid}"
    # one span per tick, not per slot: tick spans <= decode steps + 1
    tick_spans = [r for r in recs if r["name"] == "decode.tick"]
    assert len(tick_spans) == eng.counters["decode_steps"]


def test_decode_preemption_is_a_span_event(sink):
    from paddle_tpu.inference.decode import (DecodeEngine,
                                             DecodeModelConfig)

    cfg = DecodeModelConfig(vocab_size=32, n_layers=1, n_heads=2,
                            head_dim=8, ffn_dim=16, max_context=24)
    eng = DecodeEngine(cfg, seed=7, max_batch=2, n_pages=8, page_size=4,
                       max_pages_per_seq=6)
    eng.warm()
    hs = [eng.submit(p, max_new_tokens=10)
          for p in ([1, 2, 3, 4, 5], [6, 7, 8, 9, 10, 11])]
    _drive(eng)
    for h in hs:
        h.result(timeout=5)
    assert eng.counters["decode_preempted"] >= 1
    roots = [r for r in _spans(sink) if r["name"] == "decode.request"]
    preempted = [r for r in roots
                 if any(e["name"] == "preempted"
                        for e in r.get("events", ()))]
    assert preempted, "no root span carries the preemption event"
    # the preempted request re-queued: a second decode.queue span
    # exists under its root, flagged as a preemption requeue
    pr = preempted[0]
    queues = [r for r in _spans(sink)
              if r["name"] == "decode.queue"
              and r["parent"] == pr["span"]]
    assert len(queues) >= 2
    assert any(r.get("attrs", {}).get("requeued_after_preemption")
               for r in queues)
    assert pr["attrs"]["preempted"] >= 1


# ---------------------------------------------------------------------------
# serving engine lifecycle spans
# ---------------------------------------------------------------------------
def _serving_engine(tmp_path):
    import paddle_tpu.static as static
    from paddle_tpu.inference.serving import (AnalysisPredictor,
                                              ServingEngine)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 4])
        y = static.nn.fc(x, 3)
    exe = static.Executor()
    exe.run(startup)
    model_dir = str(tmp_path / "blob")
    static.save_inference_model(model_dir, ["x"], [y], exe,
                                main_program=main)
    pred = AnalysisPredictor(model_dir, batch_buckets=(1, 2, 4))
    pred.warm()
    return ServingEngine(pred)


def test_serving_request_spans_and_typed_deadline(sink, tmp_path):
    from paddle_tpu.inference.serving import DeadlineExceeded

    eng = _serving_engine(tmp_path)
    h = eng.submit({"x": np.ones((2, 4), np.float32)})
    eng.run_once()
    h.result(timeout=5)
    # unmakeable deadline: typed status on the root span
    eng.min_service_s = 10.0
    with pytest.raises(DeadlineExceeded):
        eng.submit({"x": np.ones((1, 4), np.float32)}, deadline_s=0.5)
    recs = _spans(sink)
    root = next(r for r in recs if r["name"] == "serve.request"
                and r["status"] == "ok")
    children = [r for r in recs if r.get("parent") == root["span"]]
    assert {"serve.queue"} <= {r["name"] for r in children}
    dispatch = next(r for r in recs if r["name"] == "serve.dispatch")
    assert root["trace"] in dispatch["attrs"]["requests"]
    assert dispatch["attrs"]["n_requests"] == 1
    shed = next(r for r in recs if r["name"] == "serve.request"
                and r["status"] == "DeadlineExceeded")
    assert shed["span"] != root["span"]


# ---------------------------------------------------------------------------
# PS wire propagation (cross-process header) + failover
# ---------------------------------------------------------------------------
def test_ps_rpc_span_links_to_caller_trace(sink):
    from paddle_tpu.ps.service import PSClient, PSServer
    from paddle_tpu.ps.table import SparseTable

    srv = PSServer({0: SparseTable(4, init_range=0.0, seed=1)}).start()
    c = PSClient(endpoints=[srv.endpoint])
    ids = np.arange(8, dtype=np.int64)
    try:
        with tracing.span("train.step", parent=False) as sp:
            caller = sp.context()
            c.push(0, ids, np.ones((8, 4), np.float32), 4, lr=0.5)
            c.pull(0, ids, 4)
        # untraced RPC: no span context on the wire, no server span
        c.pull(0, ids, 4)
    finally:
        c.close()
        srv.stop()
    recs = _spans(sink)
    server_side = [r for r in recs if r["name"] == "ps_rpc"]
    assert {r["attrs"]["op"] for r in server_side} == {"push", "pull"}
    for r in server_side:
        # the server's span landed in the CALLER's tree across the wire
        assert r["trace"] == format(caller.trace_id, "016x")
        assert r["parent"] == format(caller.span_id, "016x")
        assert r["status"] == "ok"
    # exactly one traced pull: the untraced one produced no span
    assert sum(1 for r in server_side
               if r["attrs"]["op"] == "pull") == 1


@pytest.mark.slow
def test_ps_rpc_spans_parent_across_failover(sink, tmp_path):
    """Chaos-drill shape: primary dies mid-job; the client's next
    traced write fails over to the promoted backup and the NEW
    server-side span still lands in the caller's trace."""
    from paddle_tpu.distributed.http_kv import KVClient, KVServer
    from paddle_tpu.ps.replication import (ReplicaCoordinator,
                                           ReplicatedPSServer)
    from paddle_tpu.ps.service import PSClient
    from paddle_tpu.ps.table import SparseTable

    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    kv_srv = KVServer(free_port())
    kv_srv.start()
    kv = KVClient(
        f"127.0.0.1:{kv_srv.http_server.server_address[1]}")
    pa, pb = free_port(), free_port()
    coord = ReplicaCoordinator(kv, job="j", lease_ttl=0.3,
                               boot_grace=60.0)
    coord.publish([[f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"]], sync=True)
    a = ReplicatedPSServer({0: SparseTable(4, init_range=0.0, seed=1)},
                           kv, job="j", port=pa, lease_ttl=0.3).start()
    b = ReplicatedPSServer({0: SparseTable(4, init_range=0.0, seed=1)},
                           kv, job="j", port=pb, lease_ttl=10.0).start()
    c = PSClient(kv=kv, job="j", failover_timeout=10.0)
    ids = np.arange(6, dtype=np.int64)
    ones = np.ones((6, 4), np.float32)
    try:
        with tracing.span("train.step", parent=False) as sp:
            caller_trace = format(sp.trace_id, "016x")
            c.push(0, ids, ones, 4, lr=0.5)
            a.crash()
            time.sleep(0.5)          # A's lease lapses; B's holds
            assert coord.check_now() == [0]
            # failover + replay inside the SAME traced region
            c.push(0, ids, ones, 4, lr=0.5)
            np.testing.assert_allclose(c.pull(0, ids, 4), -1.0)
    finally:
        c.close()
        a.stop()
        b.stop()
        kv_srv.stop()
    server_side = [r for r in _spans(sink) if r["name"] == "ps_rpc"]
    eps = {r["attrs"]["endpoint"] for r in server_side
           if r["attrs"]["op"] == "push"}
    # both generations served a traced push: the dead primary AND the
    # promoted backup link into the one caller trace
    assert eps == {a.endpoint, b.endpoint}
    assert all(r["trace"] == caller_trace for r in server_side)


# ---------------------------------------------------------------------------
# http_kv propagation
# ---------------------------------------------------------------------------
def test_http_kv_spans_link_via_headers(sink):
    from paddle_tpu.distributed.http_kv import KVClient, KVServer

    srv = KVServer(0)
    srv.start()
    port = srv.http_server.server_address[1]
    c = KVClient(f"127.0.0.1:{port}")
    try:
        with tracing.span("rendezvous", parent=False) as sp:
            c.put("scope/k", b"v")
            assert c.get("scope/k") == b"v"
        c.get("scope/k")       # untraced: no server span
    finally:
        srv.stop()
    recs = [r for r in _spans(sink)
            if r["name"].startswith("http_kv.")]
    assert {r["name"] for r in recs} == {"http_kv.PUT", "http_kv.GET"}
    for r in recs:
        assert r["trace"] == format(sp.trace_id, "016x")
        assert r["parent"] == format(sp.span_id, "016x")
    assert sum(1 for r in recs if r["name"] == "http_kv.GET") == 1


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------
def test_federation_merges_with_instance_labels():
    from paddle_tpu.observability.federation import FederatedMetrics
    from paddle_tpu.observability.metrics import parse_prometheus_text

    texts = {
        "a:1": "# TYPE serve_requests counter\nserve_requests 5\n",
        "b:2": "# TYPE serve_requests counter\nserve_requests 7\n",
    }

    def fetch(ep, timeout=None):
        return texts[ep]

    fed = FederatedMetrics(["a:1", "b:2"], clock=lambda: 100.0,
                           fetch=fetch)
    assert fed.scrape_once() == {"a:1": True, "b:2": True}
    merged = parse_prometheus_text(fed.render())
    assert merged['serve_requests{instance="a:1"}'] == 5
    assert merged['serve_requests{instance="b:2"}'] == 7
    assert merged['federation_target_up{instance="a:1"}'] == 1
    # TYPE header survives the merge exactly once
    assert fed.render().count("# TYPE serve_requests counter") == 1


def test_federation_survives_killed_endpoint_mid_scrape():
    """Satellite acceptance: a member dies between scrapes — the
    staleness gauge is set, the merged output still renders (stale
    samples kept), and the scrape NEVER raises."""
    from paddle_tpu.observability.federation import FederatedMetrics
    from paddle_tpu.observability.metrics import (default_registry,
                                                  parse_prometheus_text)

    clk = [100.0]
    alive = {"a:1": True, "b:2": True}
    texts = {"a:1": "decode_requests 3\n", "b:2": "decode_requests 9\n"}

    def fetch(ep, timeout=None):
        if not alive[ep]:
            raise ConnectionRefusedError(f"{ep} is dead")
        return texts[ep]

    fed = FederatedMetrics(["a:1", "b:2"], clock=lambda: clk[0],
                           fetch=fetch)
    fed.scrape_once()
    alive["b:2"] = False       # killed mid-scrape-cycle
    clk[0] = 160.0
    assert fed.scrape_once() == {"a:1": True, "b:2": False}
    merged = parse_prometheus_text(fed.render())
    # the dead member's last good samples still serve, staleness visible
    assert merged['decode_requests{instance="b:2"}'] == 9
    assert merged['federation_target_up{instance="b:2"}'] == 0
    assert merged['federation_scrape_age_s{instance="b:2"}'] == 60.0
    assert merged['federation_target_up{instance="a:1"}'] == 1
    assert fed.staleness()["b:2"] == 60.0
    reg = default_registry()
    assert reg.get("federation_target_up") \
        .value(instance="b:2") == 0
    assert reg.flat_snapshot().get("federation_scrape_failures", 0) >= 1


def test_federation_server_real_listeners():
    """End to end over real sockets: two /metrics listeners federated
    onto one; killing one flips its up gauge on the next cycle."""
    from paddle_tpu.observability.federation import (FederationServer,
                                                     scrape_text)
    from paddle_tpu.observability.metrics import parse_prometheus_text
    from paddle_tpu.observability.server import MetricsServer

    m1, m2 = MetricsServer(0).start(), MetricsServer(0).start()
    eps = [f"127.0.0.1:{m1.port}", f"127.0.0.1:{m2.port}"]
    fed = FederationServer(eps, interval_s=3600)   # manual cycles
    fed.start()
    try:
        text = scrape_text(f"127.0.0.1:{fed.port}")
        merged = parse_prometheus_text(text)
        for ep in eps:
            assert merged[f'federation_target_up{{instance="{ep}"}}'] \
                == 1
        m2.stop()
        fed.federation.scrape_once()
        merged = parse_prometheus_text(
            scrape_text(f"127.0.0.1:{fed.port}"))
        assert merged[
            f'federation_target_up{{instance="{eps[1]}"}}'] == 0
        assert merged[
            f'federation_target_up{{instance="{eps[0]}"}}'] == 1
    finally:
        fed.stop()
        m1.stop()
        from paddle_tpu.observability.server import stop_metrics_server
        stop_metrics_server()


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------
def _hist_samples(name, cums, bounds=(1.0, 10.0, 100.0)):
    out = {}
    for b, c in zip(list(bounds) + ["+Inf"], cums):
        out[f'{name}_bucket{{le="{b}"}}'] = c
    return out


def test_objective_burn_from_bucket_deltas():
    from paddle_tpu.observability.slo import Objective

    o = Objective("p99", hist="serve_e2e_ms", percentile=99,
                  threshold_ms=100.0)
    old = _hist_samples("serve_e2e_ms", (90, 95, 100, 100))
    # delta: 100 new events, 5 past 100ms -> bad 5%, burn 5
    new = _hist_samples("serve_e2e_ms", (180, 190, 195, 200))
    assert o.bad_fraction(new, old) == pytest.approx(0.05)
    assert o.burn_rate(new, old) == pytest.approx(5.0)
    # counter reset: negative delta falls back to the new totals
    shrunk = _hist_samples("serve_e2e_ms", (10, 10, 10, 10))
    assert o.bad_fraction(shrunk, old) == pytest.approx(0.0)
    # empty window: no signal, never a burn
    assert o.burn_rate(new, new) is None


def test_multi_window_evaluator_fake_clock():
    from paddle_tpu.observability.slo import Objective, SLOEvaluator

    o = Objective("err", numerator="serve_failed",
                  denominator="serve_requests", max_ratio=0.01)
    ev = SLOEvaluator([o], windows=((60.0, 10.0), (600.0, 2.0)),
                      clock=lambda: 0.0, publish=False)
    # long healthy history, then a short error spike: the fast window
    # burns, the slow window absorbs it -> NOT burning (de-noised)
    ev.add_snapshot({"serve_requests": 0, "serve_failed": 0}, t=0.0)
    ev.add_snapshot({"serve_requests": 10000, "serve_failed": 0},
                    t=540.0)
    ev.add_snapshot({"serve_requests": 10100, "serve_failed": 30},
                    t=610.0)
    v = ev.evaluate()[0]
    fast, slow = v.windows
    assert fast["burn_rate"] > 10.0
    assert slow["burn_rate"] < 2.0
    assert not v.burning
    # sustained burn: BOTH windows exceed -> burning
    ev2 = SLOEvaluator([o], windows=((60.0, 10.0), (600.0, 2.0)),
                       clock=lambda: 0.0, publish=False)
    ev2.add_snapshot({"serve_requests": 0, "serve_failed": 0}, t=0.0)
    ev2.add_snapshot({"serve_requests": 9000, "serve_failed": 4000},
                     t=540.0)
    ev2.add_snapshot({"serve_requests": 10000, "serve_failed": 4500},
                     t=610.0)
    assert ev2.evaluate()[0].burning


def test_evaluator_publishes_verdict_gauges():
    from paddle_tpu.observability.metrics import default_registry
    from paddle_tpu.observability.slo import Objective, SLOEvaluator

    o = Objective("pub_err", numerator="decode_failed",
                  denominator="decode_requests", max_ratio=0.01)
    ev = SLOEvaluator([o], windows=((60.0, 1.0),), clock=lambda: 0.0)
    ev.add_snapshot({"decode_requests": 100, "decode_failed": 50},
                    t=0.0)
    reg = default_registry()
    before = reg.flat_snapshot().get("slo_breaches", 0)
    verdicts = ev.evaluate()
    assert [v.objective for v in verdicts if v.burning] == ["pub_err"]
    # burning() is a read: it must not re-publish/re-count the breach
    assert ev.burning() == ["pub_err"]
    assert reg.flat_snapshot().get("slo_breaches", 0) - before == 1
    assert reg.get("slo_burning").value(objective="pub_err") == 1
    assert reg.get("slo_burn_rate") \
        .value(objective="pub_err", window="60s") == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# CLIs: slo_check + trace_view
# ---------------------------------------------------------------------------
def _run_cli(args):
    return subprocess.run([sys.executable] + args, cwd=REPO,
                          capture_output=True, text=True, timeout=120)


def test_slo_check_cli_exit_codes(tmp_path):
    healthy = tmp_path / "healthy.txt"
    healthy.write_text(
        "\n".join(f'decode_e2e_ms_bucket{{le="{b}"}} {c}'
                  for b, c in (("100", 99), ("2500", 100),
                               ("+Inf", 100)))
        + "\ndecode_requests 100\ndecode_failed 0\n"
        + "serve_requests 10\nserve_failed 0\n")
    burned = tmp_path / "burned.txt"
    burned.write_text(
        "\n".join(f'decode_e2e_ms_bucket{{le="{b}"}} {c}'
                  for b, c in (("100", 1), ("2500", 5), ("+Inf", 100)))
        + "\ndecode_requests 100\ndecode_failed 0\n")
    r = _run_cli(["tools/slo_check.py", "--metrics", str(healthy)])
    assert r.returncode == 0, r.stderr + r.stdout
    assert "BURNING" not in r.stdout
    r = _run_cli(["tools/slo_check.py", "--metrics", str(burned)])
    assert r.returncode == 1, r.stderr + r.stdout
    assert "decode_e2e_p99" in r.stdout and "BURNING" in r.stdout
    r = _run_cli(["tools/slo_check.py", "--metrics", str(burned),
                  "--json"])
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert "decode_e2e_p99" in doc["burning"]
    r = _run_cli(["tools/slo_check.py", "--metrics",
                  str(tmp_path / "missing.txt")])
    assert r.returncode == 2


def test_trace_view_cli_tree_and_refusal(tmp_path):
    path = str(tmp_path / "t.jsonl")
    enable_step_trace(path)
    clk = [0.0]

    def clock():
        clk[0] += 0.1
        return clk[0]

    try:
        slow = tracing.Span("decode.request", parent=False, clock=clock)
        q = tracing.Span("decode.queue", parent=slow, clock=clock)
        q.end()
        p = tracing.Span("decode.prefill", parent=slow, clock=clock)
        p.event("preempted", slot=0)
        p.end()
        tick = tracing.Span(
            "decode.tick", parent=False, clock=clock,
            requests=[format(slow.trace_id, "016x")])
        tick.end()
        slow.end()
        fast = tracing.Span("decode.request", parent=False)
        fast.end()
    finally:
        disable_step_trace()
    tid = format(slow.trace_id, "016x")
    r = _run_cli(["tools/trace_view.py", path, "--slowest", "1"])
    assert r.returncode == 0, r.stderr
    assert tid in r.stdout      # the slowest root is the slow trace
    r = _run_cli(["tools/trace_view.py", path, "--trace", tid])
    assert r.returncode == 0, r.stderr
    assert "decode.prefill" in r.stdout
    assert "preempted" in r.stdout
    assert "critical path" in r.stdout
    assert "decode.tick" in r.stdout    # referenced batch tick folded in
    # unknown schema: refuse with exit 2, like perf_report
    bad = tmp_path / "future.jsonl"
    bad.write_text(json.dumps({"schema": 99, "kind": "span",
                               "trace": "x", "span": "y"}) + "\n")
    r = _run_cli(["tools/trace_view.py", str(bad)])
    assert r.returncode == 2
    assert "unknown step-trace schema" in r.stderr


# ---------------------------------------------------------------------------
# flight recorder names stranded requests
# ---------------------------------------------------------------------------
def test_flight_dump_names_inflight_requests(tmp_path):
    from paddle_tpu.observability.flight_recorder import FlightRecorder

    fr = FlightRecorder(capacity=8, dir=str(tmp_path))
    sp = tracing.Span("decode.request", parent=False, root=True)
    tid = format(sp.trace_id, "016x")
    try:
        path = fr.note_error(RuntimeError("chaos kill"),
                             where="decode.step")
        dump = json.load(open(path))
        stranded = {s["trace"]: s for s in dump["inflight_requests"]}
        assert tid in stranded
        assert stranded[tid]["name"] == "decode.request"
        assert stranded[tid]["span"] == format(sp.span_id, "016x")
    finally:
        sp.end()
    # after the request resolves, a new dump no longer strands it
    # (other suite tests' genuinely-stranded requests may remain)
    dump = json.load(open(fr.dump(reason="after")))
    assert tid not in {s["trace"] for s in dump["inflight_requests"]}


# ---------------------------------------------------------------------------
# load_gen stamps trace ids
# ---------------------------------------------------------------------------
def test_decode_load_gen_reports_slowest_traces(sink):
    from paddle_tpu.inference.decode import (DecodeEngine,
                                             DecodeModelConfig)
    from tools.load_gen import DecodeLoadGen

    cfg = DecodeModelConfig(vocab_size=32, n_layers=1, n_heads=2,
                            head_dim=8, ffn_dim=16, max_context=32)
    eng = DecodeEngine(cfg, seed=3, max_batch=2, n_pages=16, page_size=4,
                       max_pages_per_seq=8)
    eng.warm()
    eng.start()
    try:
        summary = DecodeLoadGen(eng, total_requests=4, workers=2,
                                prompt_lens=(2, 3), output_lens=(2,),
                                timeout_s=60).run()
    finally:
        eng.drain(timeout=30)
    assert summary["ok"] == 4
    slowest = summary["slowest_traces"]
    assert slowest and len(slowest[0]["trace_id"]) == 16
    assert slowest == sorted(slowest, key=lambda r: -r["ms"])
    # the reported ids resolve to real span trees in the JSONL
    traces = {r["trace"] for r in _spans(sink)}
    for row in slowest:
        assert row["trace_id"] in traces
