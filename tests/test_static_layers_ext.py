"""fluid.layers long-tail static ops (static/layers_ext.py) executed
through Program/Executor — values vs numpy/eager ground truth, parameter
layers trained via append_backward to prove the traced-vjp path works
through delegate kernels (reference fluid/tests/unittests/test_layers.py
breadth pattern)."""
import numpy as np
import pytest

import paddle_tpu.static as static


def _run(build, feeds=None, n_out=1):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        outs = build()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
    exe = static.Executor()
    exe.run(startup)
    res = exe.run(main, feed=feeds or {}, fetch_list=list(outs))
    return [np.asarray(r) for r in res]


def test_activation_family_values():
    x = np.linspace(-3, 3, 13).astype(np.float32)

    def build():
        v = static.data("x", [13])
        return [static.elu(v, 1.5), static.swish(v), static.mish(v),
                static.selu(v), static.hard_sigmoid(v), static.relu6(v),
                static.brelu(v, 1.0, 2.0), static.stanh(v),
                static.hard_swish(v), static.soft_relu(v),
                static.sign(v), static.pow(v, 2.0)]

    outs = _run(build, {"x": x})
    np.testing.assert_allclose(
        outs[0], np.where(x > 0, x, 1.5 * (np.exp(x) - 1)), atol=1e-5)
    np.testing.assert_allclose(outs[1], x / (1 + np.exp(-x)), atol=1e-5)
    np.testing.assert_allclose(outs[5], np.clip(x, 0, 6), atol=1e-6)
    np.testing.assert_allclose(outs[6], np.clip(x, 1, 2), atol=1e-6)
    np.testing.assert_allclose(outs[10], np.sign(x), atol=0)
    np.testing.assert_allclose(outs[11], x * x, atol=1e-4)


def test_elementwise_logical_reduce():
    a = np.array([[2.0, 3.0], [4.0, 5.0]], np.float32)
    b = np.array([[2.0, 2.0], [3.0, 2.0]], np.float32)

    def build():
        x = static.data("a", [2, 2])
        y = static.data("b", [2, 2])
        t = static.equal(x, y)
        f = static.less_than(x, y)
        return [static.elementwise_pow(x, y), static.elementwise_mod(x, y),
                static.elementwise_floordiv(x, y),
                static.logical_or(t, f), static.logical_xor(t, t),
                static.reduce_prod(x, dim=1),
                static.reduce_all(t), static.reduce_any(t)]

    outs = _run(build, {"a": a, "b": b})
    np.testing.assert_allclose(outs[0], a ** b)
    np.testing.assert_allclose(outs[1], np.mod(a, b))
    np.testing.assert_allclose(outs[2], np.floor_divide(a, b))
    np.testing.assert_allclose(outs[5], [6.0, 20.0])
    assert outs[6] == np.all(a == b)
    assert outs[7] == np.any(a == b)


def test_shape_introspection_and_sum():
    x = np.ones((3, 4), np.float32)

    def build():
        v = static.data("x", [3, 4])
        return [static.shape(v), static.rank(v), static.size(v),
                static.sum([v, v, v])]

    s, r, n, total = _run(build, {"x": x})
    assert list(s) == [3, 4] and r == 2 and n == 12
    np.testing.assert_allclose(total, 3 * x)


def test_manipulation_group():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)

    def build():
        v = static.data("x", [3, 4])
        idx = static.data("idx", [2, 2], dtype="int64")
        return [static.expand(v, [2, 1]),
                static.strided_slice(v, axes=[1], starts=[0], ends=[4],
                                     strides=[2]),
                static.gather_nd(v, idx),
                static.pad(v, [1, 1, 0, 0], pad_value=9.0),
                static.crop_tensor(v, shape=[2, 2], offsets=[1, 1]),
                static.unstack(v, axis=0)[1]]

    idx = np.array([[0, 1], [2, 3]], np.int64)
    outs = _run(build, {"x": x, "idx": idx})
    np.testing.assert_allclose(outs[0], np.tile(x, (2, 1)))
    np.testing.assert_allclose(outs[1], x[:, ::2])
    np.testing.assert_allclose(outs[2], [1.0, 11.0])
    assert outs[3].shape == (5, 4) and outs[3][0, 0] == 9.0
    np.testing.assert_allclose(outs[4], x[1:3, 1:3])
    np.testing.assert_allclose(outs[5], x[1])


def test_norm_and_feature_ops():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)

    def build():
        v = static.data("x", [2, 4, 8, 8])
        return [static.instance_norm(v), static.group_norm(v, groups=2),
                static.l2_normalize(v, axis=1), static.lrn(v),
                static.space_to_depth(v, 2), static.pixel_shuffle(v, 2),
                static.shuffle_channel(v, 2),
                static.adaptive_pool2d(v, [2, 2], "avg")]

    outs = _run(build, {"x": x})
    inorm = outs[0]
    np.testing.assert_allclose(inorm.mean(axis=(2, 3)), 0.0, atol=1e-4)
    np.testing.assert_allclose(inorm.std(axis=(2, 3)), 1.0, atol=1e-2)
    assert outs[4].shape == (2, 16, 4, 4)
    assert outs[5].shape == (2, 1, 16, 16)
    assert outs[7].shape == (2, 4, 2, 2)
    np.testing.assert_allclose(
        outs[7][0, 0], x[0, 0].reshape(2, 4, 2, 4).mean(axis=(1, 3)),
        atol=1e-5)


def test_resize_and_grid_ops():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def build():
        v = static.data("x", [1, 1, 4, 4])
        theta = static.data("theta", [1, 2, 3])
        grid = static.affine_grid(theta, [1, 1, 4, 4])
        return [static.resize_nearest(v, out_shape=[8, 8],
                                      align_corners=False),
                static.resize_bilinear(v, out_shape=[2, 2],
                                       align_corners=True),
                static.grid_sampler(v, grid)]

    theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
    outs = _run(build, {"x": x, "theta": theta})
    assert outs[0].shape == (1, 1, 8, 8)
    np.testing.assert_allclose(outs[0][0, 0, ::2, ::2], x[0, 0])
    assert outs[1].shape == (1, 1, 2, 2)
    # identity affine grid reproduces the input
    np.testing.assert_allclose(outs[2], x, atol=1e-4)


def test_conv_pool_long_tail_shapes():
    rng = np.random.RandomState(0)
    x4 = rng.randn(2, 3, 8, 8).astype(np.float32)
    x5 = rng.randn(2, 3, 4, 8, 8).astype(np.float32)

    def build():
        v4 = static.data("x4", [2, 3, 8, 8])
        v5 = static.data("x5", [2, 3, 4, 8, 8])
        return [static.conv2d_transpose(v4, 6, filter_size=2, stride=2),
                static.conv3d(v5, 4, filter_size=3, padding=1),
                static.pool3d(v5, 2, "max", 2),
                static.adaptive_pool3d(v5, [2, 2, 2], "avg")]

    outs = _run(build, {"x4": x4, "x5": x5})
    assert outs[0].shape == (2, 6, 16, 16)
    assert outs[1].shape == (2, 4, 4, 8, 8)
    assert outs[2].shape == (2, 3, 2, 4, 4)
    assert outs[3].shape == (2, 3, 2, 2, 2)


def test_losses_and_misc():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 5).astype(np.float32)
    y = rng.rand(4, 5).astype(np.float32)

    f1 = rng.randn(2, 3, 4, 4).astype(np.float32)
    f2 = rng.randn(2, 5, 4, 4).astype(np.float32)

    def build():
        a = static.data("x", [4, 5])
        b = static.data("y", [4, 5])
        lbl = static.data("lbl", [4, 5])
        fa = static.data("f1", [2, 3, 4, 4])
        fb = static.data("f2", [2, 5, 4, 4])
        return [static.smooth_l1(a, b), static.log_loss(a, b),
                static.label_smooth(lbl, epsilon=0.1),
                static.clip_by_norm(a, 1.0),
                static.fsp_matrix(fa, fb)]

    outs = _run(build, {"x": x, "y": y, "lbl": y, "f1": f1, "f2": f2})
    assert outs[0].shape == (4, 1)
    np.testing.assert_allclose(outs[2], 0.9 * y + 0.1 / 5, atol=1e-6)
    assert np.linalg.norm(outs[3]) <= 1.0 + 1e-5
    assert outs[4].shape == (2, 3, 5)


def test_random_ops_shapes_and_ranges():
    def build():
        probs = static.data("p", [4, 6])
        return [static.uniform_random([3, 4], min=-2.0, max=2.0),
                static.gaussian_random([64], std=2.0),
                static.sampling_id(probs),
                static.random_crop(probs, shape=[3])]

    p = np.full((4, 6), 1.0 / 6, np.float32)
    outs = _run(build, {"p": p})
    assert outs[0].shape == (3, 4) and (np.abs(outs[0]) <= 2).all()
    assert outs[1].shape == (64,)
    assert outs[2].shape == (4,) and (outs[2] < 6).all()
    assert outs[3].shape == (4, 3)


def test_crf_static_matches_eager():
    import jax.numpy as jnp

    from paddle_tpu.nn import crf as crf_mod

    rng = np.random.RandomState(0)
    B, L, T = 2, 5, 3
    em = rng.randn(B, L, T).astype(np.float32)
    lbl = rng.randint(0, T, (B, L)).astype(np.int64)
    lens = np.array([5, 3], np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        e = static.data("em", [B, L, T])
        la = static.data("lbl", [B, L], dtype="int64")
        ln = static.data("lens", [B], dtype="int64")
        ll = static.linear_chain_crf(e, la, length=ln)
    exe = static.Executor()
    exe.run(startup)
    (got,) = exe.run(main, feed={"em": em, "lbl": lbl, "lens": lens},
                     fetch_list=[ll])
    # same transition init as the static parameter (xavier) is unknown;
    # instead check consistency: rerun eager with the trained param
    trans_name = [n for n, v in main.global_block.vars.items()
                  if "linear_chain_crf" in n][0]
    from paddle_tpu.static.executor import global_scope
    trans = np.asarray(global_scope().find_var(trans_name))
    want = crf_mod.linear_chain_crf(jnp.asarray(em), jnp.asarray(trans),
                                    jnp.asarray(lbl), jnp.asarray(lens))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(want.numpy()), atol=1e-4)


def test_param_layers_train_via_append_backward():
    """prelu + bilinear_tensor_product parameters update and reduce the
    loss — proving delegate kernels differentiate through traced-vjp."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 3).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        a = static.data("x", [8, 4])
        t = static.data("y", [8, 3])
        h = static.prelu(a, mode="all")
        out = static.bilinear_tensor_product(h, h, 3)
        loss = static.reduce_mean(static.square_error_cost(out, t))
        static.SGD(learning_rate=0.05).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    losses = [float(np.asarray(exe.run(main, feed={"x": x, "y": y},
                                       fetch_list=[loss])[0]))
              for _ in range(15)]
    assert losses[-1] < losses[0] * 0.8, losses


def test_multiplex_and_mean_iou():
    a = np.zeros((3, 2), np.float32)
    b = np.ones((3, 2), np.float32)
    idx = np.array([[0], [1], [0]], np.int32)

    def build():
        va = static.data("a", [3, 2])
        vb = static.data("b", [3, 2])
        vi = static.data("i", [3, 1], dtype="int32")
        pred = static.data("pred", [6], dtype="int64")
        lbl = static.data("lbl", [6], dtype="int64")
        m = static.mean_iou(pred, lbl, 2)
        return [static.multiplex([va, vb], vi), m[0]]

    pred = np.array([0, 0, 1, 1, 0, 1], np.int64)
    lbl = np.array([0, 1, 1, 1, 0, 0], np.int64)
    outs = _run(build, {"a": a, "b": b, "i": idx, "pred": pred, "lbl": lbl})
    np.testing.assert_allclose(outs[0], [[0, 0], [1, 1], [0, 0]])
    assert 0.0 < float(outs[1]) < 1.0
