"""Round-3 detection long-tail ops vs numpy transliterations of the
reference kernels (operators/detection/: target_assign_op.h,
polygon_box_transform_op.cc, box_decoder_and_assign_op.h,
locality_aware_nms_op.cc, retinanet_detection_output_op.cc,
collect_fpn_proposals_op.h, generate_proposal_labels_op.cc,
generate_mask_labels_op.cc, roi_perspective_transform_op.cc,
detection_map_op.h)."""
import math

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (backend bring-up guard)
from paddle_tpu.vision import ops as vops
from paddle_tpu.vision import rcnn


def _np(x):
    import jax
    if hasattr(x, "value"):
        x = x.value
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return np.asarray(x)


# ---------------------------------------------------------------------------
# target_assign
# ---------------------------------------------------------------------------


def test_target_assign_matches_reference_loop():
    rng = np.random.RandomState(0)
    n, m, p, k = 3, 5, 1, 4
    lengths = np.asarray([2, 3, 1])
    x = rng.randn(int(lengths.sum()), p, k).astype(np.float32)
    match = np.full((n, m), -1, np.int32)
    match[0, 0] = 1
    match[0, 3] = 0
    match[1, 2] = 2
    match[2, 4] = 0
    neg = np.asarray([1, 0, 2], np.int32)   # flat negative columns
    neg_len = np.asarray([1, 1, 1])

    out, wt = vops.target_assign(x, match, lengths=lengths,
                                 neg_indices=neg, neg_lengths=neg_len,
                                 mismatch_value=0)
    out, wt = _np(out), _np(wt)

    off = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    exp = np.zeros((n, m, k), np.float32)
    exp_w = np.zeros((n, m, 1), np.float32)
    for i in range(n):
        for j in range(m):
            idx = match[i, j]
            if idx > -1:
                exp[i, j] = x[off[i] + idx, j % p]
                exp_w[i, j] = 1.0
    pos = 0
    for i in range(n):
        for _ in range(neg_len[i]):
            exp[i, neg[pos]] = 0.0
            exp_w[i, neg[pos]] = 1.0
            pos += 1
    np.testing.assert_allclose(out, exp, rtol=1e-6)
    np.testing.assert_allclose(wt, exp_w)


# ---------------------------------------------------------------------------
# polygon_box_transform
# ---------------------------------------------------------------------------


def test_polygon_box_transform_formula():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 3, 5).astype(np.float32)
    out = _np(vops.polygon_box_transform(x))
    exp = np.empty_like(x)
    for nn in range(2):
        for c in range(4):
            for h in range(3):
                for w in range(5):
                    if c % 2 == 0:
                        exp[nn, c, h, w] = w * 4 - x[nn, c, h, w]
                    else:
                        exp[nn, c, h, w] = h * 4 - x[nn, c, h, w]
    np.testing.assert_allclose(out, exp, rtol=1e-6)


# ---------------------------------------------------------------------------
# box_decoder_and_assign
# ---------------------------------------------------------------------------


def test_box_decoder_and_assign_vs_loop():
    rng = np.random.RandomState(2)
    r, c = 6, 4
    prior = np.abs(rng.randn(r, 4)).astype(np.float32) * 10
    prior[:, 2:] += prior[:, :2] + 5
    var = np.asarray([0.1, 0.1, 0.2, 0.2], np.float32)
    tb = rng.randn(r, 4 * c).astype(np.float32) * 0.3
    sc = rng.rand(r, c).astype(np.float32)
    clip = 4.135
    dec, asg = vops.box_decoder_and_assign(prior, var, tb, sc, clip)
    dec, asg = _np(dec), _np(asg)

    exp = np.zeros((r, c * 4), np.float32)
    exp_a = np.zeros((r, 4), np.float32)
    for i in range(r):
        pw = prior[i, 2] - prior[i, 0] + 1
        ph = prior[i, 3] - prior[i, 1] + 1
        pcx = prior[i, 0] + pw / 2
        pcy = prior[i, 1] + ph / 2
        for j in range(c):
            o = j * 4
            dw = min(var[2] * tb[i, o + 2], clip)
            dh = min(var[3] * tb[i, o + 3], clip)
            cx = var[0] * tb[i, o] * pw + pcx
            cy = var[1] * tb[i, o + 1] * ph + pcy
            w = math.exp(dw) * pw
            h = math.exp(dh) * ph
            exp[i, o:o + 4] = [cx - w / 2, cy - h / 2,
                               cx + w / 2 - 1, cy + h / 2 - 1]
        best, best_j = -1.0, -1
        for j in range(c):
            if sc[i, j] > best and j > 0:
                best, best_j = sc[i, j], j
        if best_j > 0:
            exp_a[i] = exp[i, best_j * 4:best_j * 4 + 4]
        else:
            exp_a[i] = prior[i]
    np.testing.assert_allclose(dec, exp, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(asg, exp_a, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# locality_aware_nms
# ---------------------------------------------------------------------------


def test_locality_aware_nms_merges_neighbours():
    # three overlapping axis-aligned boxes in input order; the first two
    # merge (score-weighted), the third is disjoint
    boxes = np.asarray([[[0, 0, 10, 10],
                         [1, 1, 11, 11],
                         [50, 50, 60, 60]]], np.float32)
    scores = np.asarray([[[0.6, 0.4, 0.9]]], np.float32)
    out, counts = vops.locality_aware_nms(
        boxes, scores, score_threshold=0.01, nms_threshold=0.3,
        normalized=False, background_label=-1)
    out, counts = _np(out), _np(counts)
    assert counts.tolist() == [2]
    # merged box: (b0*0.6 + b1*0.4) / 1.0, merged score 1.0
    merged = (boxes[0, 0] * 0.6 + boxes[0, 1] * 0.4) / 1.0
    by_score = out[np.argsort(-out[:, 1])]
    np.testing.assert_allclose(by_score[0, 1], 1.0, rtol=1e-6)
    np.testing.assert_allclose(by_score[0, 2:], merged, rtol=1e-5)
    np.testing.assert_allclose(by_score[1, 1], 0.9, rtol=1e-6)
    np.testing.assert_allclose(by_score[1, 2:], boxes[0, 2], rtol=1e-6)


def test_locality_aware_nms_quads_poly_iou():
    # two identical quads merge; poly IoU path (box_size=8)
    q = [0, 0, 10, 0, 10, 10, 0, 10]
    q2 = [1, 0, 11, 0, 11, 10, 1, 10]
    far = [100, 100, 110, 100, 110, 110, 100, 110]
    boxes = np.asarray([[q, q2, far]], np.float32)
    scores = np.asarray([[[0.5, 0.5, 0.8]]], np.float32)
    out, counts = vops.locality_aware_nms(
        boxes, scores, score_threshold=0.01, nms_threshold=0.3,
        normalized=True, background_label=-1)
    out, counts = _np(out), _np(counts)
    assert counts.tolist() == [2]
    scores_out = sorted(out[:, 1].tolist(), reverse=True)
    np.testing.assert_allclose(scores_out[0], 1.0, rtol=1e-6)


def test_poly_iou_identical_and_disjoint():
    sq = [0, 0, 4, 0, 4, 4, 0, 4]
    assert vops._np_poly_iou(sq, sq) == pytest.approx(1.0)
    sq2 = [10, 10, 14, 10, 14, 14, 10, 14]
    assert vops._np_poly_iou(sq, sq2) == pytest.approx(0.0)
    half = [2, 0, 6, 0, 6, 4, 2, 4]   # overlaps half of sq
    assert vops._np_poly_iou(sq, half) == pytest.approx(8.0 / 24.0)


# ---------------------------------------------------------------------------
# retinanet_detection_output
# ---------------------------------------------------------------------------


def test_retinanet_detection_output_decode_and_nms():
    # one level, 2 anchors, 2 classes, 1 image; zero deltas = anchors
    anchors = np.asarray([[0, 0, 9, 9], [20, 20, 29, 29]], np.float32)
    deltas = np.zeros((1, 2, 4), np.float32)
    scores = np.asarray([[[0.9, 0.1], [0.2, 0.7]]], np.float32)
    im_info = np.asarray([[100, 100, 1.0]], np.float32)
    out, counts = vops.retinanet_detection_output(
        [deltas], [scores], [anchors], im_info,
        score_threshold=0.05, nms_top_k=10, keep_top_k=10,
        nms_threshold=0.3)
    out, counts = _np(out), _np(counts)
    # single level = last level -> threshold 0, all 4 (anchor, class)
    # pairs survive (disjoint anchors, so per-class NMS keeps both)
    assert counts.tolist() == [4]
    # rows sorted by score desc: anchor0/class0 (0.9), anchor1/class1
    assert out[0, 0] == 1.0 and out[0, 1] == pytest.approx(0.9)
    np.testing.assert_allclose(out[0, 2:], [0, 0, 9, 9], atol=1e-5)
    assert out[1, 0] == 2.0 and out[1, 1] == pytest.approx(0.7)
    np.testing.assert_allclose(out[1, 2:], [20, 20, 29, 29], atol=1e-5)
    assert out[2, 1] == pytest.approx(0.2)
    assert out[3, 1] == pytest.approx(0.1)


def test_retinanet_keep_top_k_minus_one_keeps_all():
    anchors = np.asarray([[0, 0, 9, 9], [20, 20, 29, 29],
                          [40, 40, 49, 49]], np.float32)
    deltas = np.zeros((1, 3, 4), np.float32)
    scores = np.full((1, 3, 1), 0.9, np.float32)
    im_info = np.asarray([[100, 100, 1.0]], np.float32)
    _, counts = vops.retinanet_detection_output(
        [deltas], [scores], [anchors], im_info, keep_top_k=-1)
    assert _np(counts).tolist() == [3]


def test_retinanet_last_level_keeps_all_scores():
    # single (= last) level ignores score_threshold (threshold 0)
    anchors = np.asarray([[0, 0, 9, 9]], np.float32)
    deltas = np.zeros((1, 1, 4), np.float32)
    scores = np.asarray([[[0.01]]], np.float32)  # below threshold
    im_info = np.asarray([[50, 50, 1.0]], np.float32)
    out, counts = vops.retinanet_detection_output(
        [deltas], [scores], [anchors], im_info, score_threshold=0.05)
    assert _np(counts).tolist() == [1]


# ---------------------------------------------------------------------------
# roi_perspective_transform
# ---------------------------------------------------------------------------


def test_roi_perspective_transform_identity_quad():
    # an axis-aligned quad over a linear-ramp image: output approximates
    # a resampled crop; corners must match the source corners
    h = w = 16
    img = np.arange(h * w, dtype=np.float32).reshape(1, 1, h, w)
    # quad = full image corners in (x, y) order, clockwise from top-left
    rois = np.asarray([[0, 0, w - 1.0, 0, w - 1.0, h - 1.0, 0, h - 1.0]],
                      np.float32)
    out, mask, mats = vops.roi_perspective_transform(
        img, rois, lengths=np.asarray([1]), transformed_height=8,
        transformed_width=8, spatial_scale=1.0)
    out, mask = _np(out), _np(mask)
    assert out.shape == (1, 1, 8, 8)
    assert mask.shape == (1, 1, 8, 8)
    assert mask.min() == 1  # whole quad covers the image
    # top-left pixel samples source (0,0); the transform maps output
    # (0,0) -> quad corner 0
    assert out[0, 0, 0, 0] == pytest.approx(img[0, 0, 0, 0], abs=1e-3)


def test_roi_perspective_transform_outside_is_masked():
    img = np.ones((1, 1, 8, 8), np.float32)
    # degenerate-ish quad in the corner; far output columns fall outside
    rois = np.asarray([[0, 0, 3, 0, 3, 3, 0, 3]], np.float32)
    out, mask, _ = vops.roi_perspective_transform(
        img, rois, transformed_height=4, transformed_width=8)
    out, mask = _np(out), _np(mask)
    # wherever mask == 0 the output must be 0
    assert np.all(out[_np(mask) == 0] == 0.0)


# ---------------------------------------------------------------------------
# collect_fpn_proposals
# ---------------------------------------------------------------------------


def test_collect_fpn_proposals_topk_and_regroup():
    # 2 images, 2 levels
    rois_l0 = np.asarray([[0, 0, 1, 1], [2, 2, 3, 3],     # img0
                          [4, 4, 5, 5]], np.float32)       # img1
    rois_l1 = np.asarray([[6, 6, 7, 7],                    # img0
                          [8, 8, 9, 9]], np.float32)       # img1
    sc_l0 = np.asarray([0.9, 0.2, 0.8], np.float32)[:, None]
    sc_l1 = np.asarray([0.5, 0.95], np.float32)[:, None]
    lens = [np.asarray([2, 1]), np.asarray([1, 1])]
    rois, counts = rcnn.collect_fpn_proposals(
        [rois_l0, rois_l1], [sc_l0, sc_l1], 2, 3, post_nms_top_n=3,
        lengths=lens)
    rois, counts = _np(rois), _np(counts)
    # top-3 scores: 0.95 (img1), 0.9 (img0), 0.8 (img1) -> regrouped by
    # image: img0 first (0.9), then img1 (0.95, 0.8 in score order)
    assert counts.tolist() == [1, 2]
    np.testing.assert_allclose(rois[0], [0, 0, 1, 1])
    np.testing.assert_allclose(rois[1], [8, 8, 9, 9])
    np.testing.assert_allclose(rois[2], [4, 4, 5, 5])


# ---------------------------------------------------------------------------
# generate_proposal_labels
# ---------------------------------------------------------------------------


def test_generate_proposal_labels_fg_bg_split_and_targets():
    # one image, deterministic (use_random=False)
    gt_boxes = np.asarray([[0, 0, 10, 10]], np.float32)
    gt_classes = np.asarray([3], np.int32)
    is_crowd = np.asarray([0], np.int32)
    rois = np.asarray([[0, 0, 9, 9],        # IoU ~0.83 -> fg
                       [0, 0, 30, 30],      # IoU ~0.12 -> bg
                       [50, 50, 60, 60]],   # IoU 0 -> bg (lo=0 incl.)
                      np.float32)
    im_info = np.asarray([[100, 100, 1.0]], np.float32)
    cls_n = 5
    out = rcnn.generate_proposal_labels(
        rois, gt_classes, is_crowd, gt_boxes, im_info,
        rois_lengths=np.asarray([3]), gt_lengths=np.asarray([1]),
        batch_size_per_im=4, fg_fraction=0.5, fg_thresh=0.5,
        bg_thresh_hi=0.5, bg_thresh_lo=0.0,
        bbox_reg_weights=(0.1, 0.1, 0.2, 0.2), class_nums=cls_n,
        use_random=False)
    srois, labels, tgt, inw, outw, num = [_np(o) for o in out]
    assert num.tolist() == [4]
    labels = labels.reshape(-1)
    # candidates = [gt] + rois: gt (IoU 1) and roi0 are fg, rest bg
    assert (labels > 0).sum() == 2
    assert set(labels[labels > 0].tolist()) == {3}
    # fg targets live in the class-3 slot, with unit weights
    fg_rows = np.nonzero(labels > 0)[0]
    for r in fg_rows:
        assert inw[r, 12:16].tolist() == [1, 1, 1, 1]
        assert outw[r, 12:16].tolist() == [1, 1, 1, 1]
        assert np.all(inw[r, :12] == 0) and np.all(inw[r, 16:] == 0)
    # the gt-as-roi row encodes against itself -> zero deltas
    gt_row = fg_rows[np.all(np.isclose(srois[fg_rows], [0, 0, 10, 10]),
                            axis=1)][0]
    np.testing.assert_allclose(tgt[gt_row, 12:16], 0.0, atol=1e-5)


def test_generate_proposal_labels_crowd_gt_excluded():
    gt_boxes = np.asarray([[0, 0, 10, 10]], np.float32)
    gt_classes = np.asarray([2], np.int32)
    is_crowd = np.asarray([1], np.int32)   # crowd: candidate gt row
    rois = np.asarray([[40, 40, 49, 49]], np.float32)
    im_info = np.asarray([[100, 100, 1.0]], np.float32)
    out = rcnn.generate_proposal_labels(
        rois, gt_classes, is_crowd, gt_boxes, im_info,
        batch_size_per_im=4, fg_thresh=0.5, bg_thresh_hi=0.5,
        bg_thresh_lo=0.0, class_nums=3, use_random=False)
    labels = _np(out[1]).reshape(-1)
    # the crowd gt row has max_overlap forced to -1 -> not fg, not bg
    # (below bg_thresh_lo=0.0? -1 < 0 -> excluded entirely)
    assert np.all(labels == 0)
    # crowd row must not appear as fg
    assert (labels > 0).sum() == 0


# ---------------------------------------------------------------------------
# generate_mask_labels
# ---------------------------------------------------------------------------


def test_rasterize_square_polygon():
    m = 8
    box = np.asarray([0.0, 0.0, 8.0, 8.0])
    poly = [np.asarray([[0, 0], [8, 0], [8, 8], [0, 8]], np.float32)]
    mask = rcnn._rasterize_polys(poly, box, m)
    assert mask.shape == (m, m)
    assert mask.sum() == m * m          # full coverage
    half = [np.asarray([[0, 0], [4, 0], [4, 8], [0, 8]], np.float32)]
    mask2 = rcnn._rasterize_polys(half, box, m)
    assert mask2[:, :4].sum() == m * 4  # left half set
    assert mask2[:, 4:].sum() == 0


def test_generate_mask_labels_layout():
    num_classes, res = 4, 8
    im_info = np.asarray([[32, 32, 1.0]], np.float32)
    gt_classes = np.asarray([2], np.int32)
    is_crowd = np.asarray([0], np.int32)
    # one gt with one square polygon
    pts = np.asarray([[4, 4], [20, 4], [20, 20], [4, 20]], np.float32)
    rois = np.asarray([[4, 4, 20, 20],    # fg roi == poly box
                       [0, 0, 31, 31]], np.float32)
    labels = np.asarray([2, 0], np.int32)
    mask_rois, has_mask, masks, counts = rcnn.generate_mask_labels(
        im_info, gt_classes, is_crowd, pts, rois, labels,
        num_classes=num_classes, resolution=res,
        gt_lengths=np.asarray([1]), rois_lengths=np.asarray([2]),
        polys_per_gt=np.asarray([1]), points_per_poly=np.asarray([4]))
    mask_rois, has_mask, masks, counts = [
        _np(o) for o in (mask_rois, has_mask, masks, counts)]
    assert counts.tolist() == [1]
    assert has_mask.reshape(-1).tolist() == [0]
    m2 = res * res
    # class-2 slot holds the rasterized square (full coverage in the
    # roi frame), everything else is ignore (-1)
    assert np.all(masks[0, :2 * m2] == -1)
    assert np.all(masks[0, 3 * m2:] == -1)
    cls_slot = masks[0, 2 * m2:3 * m2]
    assert cls_slot.min() >= 0 and cls_slot.sum() == m2


def test_generate_mask_labels_zero_roi_image_stays_in_sync():
    # image 0 has rois, image 1 has none: outputs must stay aligned
    num_classes, res = 3, 4
    im_info = np.asarray([[32, 32, 1.0], [32, 32, 1.0]], np.float32)
    gt_classes = np.asarray([1, 1], np.int32)
    is_crowd = np.asarray([0, 0], np.int32)
    pts = np.asarray([[0, 0], [8, 0], [8, 8], [0, 8]] * 2, np.float32)
    rois = np.asarray([[0, 0, 8, 8]], np.float32)
    labels = np.asarray([1], np.int32)
    mask_rois, has_mask, masks, counts = rcnn.generate_mask_labels(
        im_info, gt_classes, is_crowd, pts, rois, labels,
        num_classes=num_classes, resolution=res,
        gt_lengths=np.asarray([1, 1]), rois_lengths=np.asarray([1, 0]),
        polys_per_gt=np.asarray([1, 1]),
        points_per_poly=np.asarray([4, 4]))
    counts = _np(counts)
    assert counts.tolist() == [1, 0]
    assert _np(mask_rois).shape[0] == counts.sum()
    assert _np(masks).shape[0] == counts.sum()


def test_generate_mask_labels_no_fg_emits_bg_guard():
    num_classes, res = 3, 4
    im_info = np.asarray([[32, 32, 1.0]], np.float32)
    gt_classes = np.asarray([1], np.int32)
    is_crowd = np.asarray([1], np.int32)   # crowd -> no usable gt
    pts = np.asarray([[0, 0], [8, 0], [8, 8], [0, 8]], np.float32)
    rois = np.asarray([[0, 0, 8, 8]], np.float32)
    labels = np.asarray([0], np.int32)
    _, has_mask, masks, counts = rcnn.generate_mask_labels(
        im_info, gt_classes, is_crowd, pts, rois, labels,
        num_classes=num_classes, resolution=res,
        gt_lengths=np.asarray([1]), rois_lengths=np.asarray([1]),
        polys_per_gt=np.asarray([1]), points_per_poly=np.asarray([4]))
    assert _np(counts).tolist() == [1]
    assert np.all(_np(masks) == -1)


# ---------------------------------------------------------------------------
# detection_map
# ---------------------------------------------------------------------------


def test_detection_map_perfect_predictions():
    det = np.asarray([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                      [2, 0.8, 0.5, 0.5, 0.9, 0.9]], np.float32)
    lab = np.asarray([[1, 0, 0.1, 0.1, 0.4, 0.4],
                      [2, 0, 0.5, 0.5, 0.9, 0.9]], np.float32)
    m_ap, state = vops.detection_map(det, lab, class_num=3,
                                     det_lengths=np.asarray([2]),
                                     label_lengths=np.asarray([2]))
    assert m_ap == pytest.approx(1.0)


def test_detection_map_false_positive_and_accumulate():
    det = np.asarray([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                      [1, 0.8, 0.6, 0.6, 0.9, 0.9]], np.float32)  # FP
    lab = np.asarray([[1, 0, 0.1, 0.1, 0.4, 0.4]], np.float32)
    m_ap, state = vops.detection_map(det, lab, class_num=2,
                                     det_lengths=np.asarray([2]),
                                     label_lengths=np.asarray([1]))
    assert m_ap == pytest.approx(1.0)   # TP ranked above FP: AP = 1
    # accumulate a second batch where the same class gets a miss
    det2 = np.asarray([[1, 0.7, 0.0, 0.0, 0.05, 0.05]], np.float32)
    lab2 = np.asarray([[1, 0, 0.5, 0.5, 0.9, 0.9]], np.float32)
    m_ap2, _ = vops.detection_map(det2, lab2, class_num=2,
                                  det_lengths=np.asarray([1]),
                                  label_lengths=np.asarray([1]),
                                  state=state)
    assert m_ap2 < 1.0                   # recall can no longer reach 1
    # 11-point flavour also runs
    m_ap3, _ = vops.detection_map(det, lab, class_num=2,
                                  det_lengths=np.asarray([2]),
                                  label_lengths=np.asarray([1]),
                                  ap_version="11point")
    assert 0.99 <= m_ap3 <= 1.01


def test_detection_map_difficult_excluded():
    det = np.asarray([[1, 0.9, 0.1, 0.1, 0.4, 0.4]], np.float32)
    lab = np.asarray([[1, 1, 0.1, 0.1, 0.4, 0.4]], np.float32)  # difficult
    m_ap, state = vops.detection_map(det, lab, class_num=2,
                                     det_lengths=np.asarray([1]),
                                     label_lengths=np.asarray([1]),
                                     evaluate_difficult=False)
    # difficult-only gt: pos_count empty for the class -> mAP 0, and the
    # matched-difficult detection is neither TP nor FP
    pos_count, true_pos, _ = state
    assert pos_count.get(1, 0) == 0 or 1 not in pos_count
    assert not true_pos.get(1)


def test_fluid_layers_facades_exist():
    from paddle_tpu.static import layers as L
    for n in ("target_assign", "polygon_box_transform",
              "box_decoder_and_assign", "roi_perspective_transform",
              "locality_aware_nms", "retinanet_detection_output",
              "detection_map", "collect_fpn_proposals",
              "generate_proposal_labels", "generate_mask_labels"):
        assert callable(getattr(L, n)), n
