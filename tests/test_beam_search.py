"""Beam search tests (reference test_beam_search_op.py /
test_beam_search_decode_op.py / rnn BeamSearchDecoder tests)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.ops.beam_search import (beam_search_decode, beam_search_step,
                                        NEG_INF)


def test_beam_search_step_selects_topk_across_beams():
    # batch=1, beam=2, vocab=3
    pre = jnp.asarray([[0.0, -1.0]])
    lp = jnp.log(jnp.asarray([[[0.1, 0.6, 0.3],
                               [0.8, 0.1, 0.1]]]))
    fin = jnp.zeros((1, 2), bool)
    scores, tok, parent, fin2 = beam_search_step(pre, lp, fin, 2, end_id=2)
    # candidates: beam0: log .6=-.51(t1), log .3=-1.2(t2), log .1=-2.3
    #             beam1: -1+log .8=-1.22(t0), ...
    assert tok.tolist() == [[1, 2]]
    assert parent.tolist() == [[0, 0]]
    assert bool(fin2[0, 1]) and not bool(fin2[0, 0])
    np.testing.assert_allclose(scores[0, 0], np.log(0.6), rtol=1e-5)


def test_beam_search_step_freezes_finished_beams():
    pre = jnp.asarray([[-0.5, -0.1]])
    lp = jnp.zeros((1, 2, 4))  # uniform-ish; irrelevant for finished beam
    fin = jnp.asarray([[False, True]])
    scores, tok, parent, fin2 = beam_search_step(pre, lp, fin, 2, end_id=3)
    # the finished beam (idx 1) survives with unchanged score via eos
    row = list(zip(tok[0].tolist(), parent[0].tolist(), scores[0].tolist()))
    frozen = [r for r in row if r[1] == 1]
    assert frozen and frozen[0][0] == 3
    np.testing.assert_allclose(frozen[0][2], -0.1, rtol=1e-5)


def test_beam_search_beats_greedy_on_garden_path():
    # vocab: 0=bos, 1=a, 2=b, 3=eos. From bos: p(a)=.6, p(b)=.4.
    # After a: uniform over {a,b} (p .5) forever. After b: eos (p ~1).
    # Greedy: bos->a->... total ~ .6*.5*.5; beam: bos->b->eos = .4.
    table = np.full((4, 4), 1e-9, np.float32)
    table[0] = [1e-9, 0.6, 0.4, 1e-9]
    table[1] = [1e-9, 0.5, 0.5 - 1e-9, 1e-9]
    table[2] = [1e-9, 1e-9, 1e-9, 1.0]
    table[3] = [1e-9, 1e-9, 1e-9, 1.0]
    log_table = jnp.log(jnp.asarray(table / table.sum(-1, keepdims=True)))

    def logits_fn(ids_buf, t, state):
        return jnp.take(log_table, ids_buf[:, t], axis=0)

    ids, scores = beam_search_decode(
        logits_fn, batch_size=1, beam_size=2, max_len=4,
        bos_id=0, eos_id=3, length_penalty=0.0)
    assert ids.shape == (1, 2, 4)
    assert ids[0, 0].tolist() == [0, 2, 3, 3]
    np.testing.assert_allclose(float(scores[0, 0]),
                               np.log(0.4) + np.log(1.0), atol=1e-4)
    # greedy path (beam 1) scores lower
    assert float(scores[0, 0]) > float(scores[0, 1])


def test_beam_search_decode_batched_and_state_gather():
    # state carries a per-beam counter; ensure gather keeps it aligned
    vocab = 5

    def logits_fn(ids_buf, t, state):
        lp = jnp.log(jnp.full((ids_buf.shape[0], vocab), 0.2))
        return lp, state + 1

    ids, scores = beam_search_decode(
        logits_fn, batch_size=3, beam_size=2, max_len=5, bos_id=1,
        eos_id=0, state=jnp.zeros((6,), jnp.int32))
    assert ids.shape == (3, 2, 5)
    assert np.all(np.asarray(ids[:, :, 0]) == 1)


@pytest.mark.slow
def test_transformer_nmt_beam_decode():
    paddle.seed(0)
    from paddle_tpu.models.transformer import TransformerNMT

    model = TransformerNMT(src_vocab_size=50, tgt_vocab_size=50,
                           d_model=32, nhead=4, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=64,
                           dropout=0.0, max_len=32)
    model.eval()
    src = paddle.to_tensor(
        np.random.RandomState(0).randint(3, 50, (2, 7)).astype("int64"))
    ids, scores = model.beam_search_decode(src, beam_size=3, max_len=10,
                                           length_penalty=0.0)
    assert tuple(ids.shape) == (2, 3, 10)
    assert np.all(ids.numpy()[:, :, 0] == 1)
    s = scores.numpy()
    assert np.all(s[:, 0] >= s[:, 1]) and np.all(s[:, 1] >= s[:, 2])

    # beam_size=1 must follow the greedy path
    ids1, _ = model.beam_search_decode(src, beam_size=1, max_len=10,
                                       length_penalty=0.0)
    greedy = model.greedy_decode(src, max_len=10).numpy()
    b1 = ids1.numpy()[:, 0, :]
    n = min(greedy.shape[1], b1.shape[1])
    np.testing.assert_array_equal(b1[:, :n], greedy[:, :n])
