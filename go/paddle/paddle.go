// Package paddle is the Go wrapper over the paddle_tpu C API
// (reference go/paddle/{predictor,config,tensor,common}.go wrapping
// paddle_c_api.h; here it wraps native/include/paddle_tpu_capi.h).
//
// Build: the capi shared library must be built first —
//   python -c "from paddle_tpu.native import capi_lib; print(capi_lib()._name)"
// then:
//   CGO_CFLAGS="-I$REPO/paddle_tpu/native/include" \
//   CGO_LDFLAGS="$CAPI_SO -Wl,-rpath,$(dirname $CAPI_SO)" go build ./...
//
// NOTE: the build image ships no Go toolchain, so this package is
// provided as source parity with the reference Go API and exercised via
// the identical C calls in tests/test_capi.py.
package paddle

// #include <stdint.h>
// #include <stdlib.h>
// #include "paddle_tpu_capi.h"
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

func lastError() error {
	return errors.New(C.GoString(C.PD_GetLastError()))
}

// Init extends sys.path of the embedded interpreter (e.g. with the
// directory containing the paddle_tpu package). Call once before use.
func Init(extraSysPath string) error {
	cs := C.CString(extraSysPath)
	defer C.free(unsafe.Pointer(cs))
	if C.PD_Init(cs) != 0 {
		return lastError()
	}
	return nil
}

// Predictor runs models exported with paddle_tpu.jit.save.
type Predictor struct {
	c *C.PD_Predictor
}

func NewPredictor(modelPrefix string) (*Predictor, error) {
	cs := C.CString(modelPrefix)
	defer C.free(unsafe.Pointer(cs))
	p := C.PD_NewPredictor(cs)
	if p == nil {
		return nil, lastError()
	}
	pred := &Predictor{c: p}
	runtime.SetFinalizer(pred, (*Predictor).finalize)
	return pred, nil
}

func (p *Predictor) finalize() { C.PD_DeletePredictor(p.c) }

func (p *Predictor) InputNum() int {
	n := int(C.PD_GetInputNum(p.c))
	runtime.KeepAlive(p)
	return n
}

func (p *Predictor) InputName(i int) string {
	s := C.GoString(C.PD_GetInputName(p.c, C.int(i)))
	runtime.KeepAlive(p)
	return s
}

func (p *Predictor) SetInputFloat(name string, data []float32,
	shape []int64) error {
	if len(data) == 0 || len(shape) == 0 {
		return errors.New("empty data or shape")
	}
	cs := C.CString(name)
	defer C.free(unsafe.Pointer(cs))
	rc := C.PD_SetInputFloat(p.c, cs, (*C.float)(&data[0]),
		(*C.int64_t)(&shape[0]), C.int(len(shape)))
	runtime.KeepAlive(p)
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (p *Predictor) SetInputInt64(name string, data []int64,
	shape []int64) error {
	if len(data) == 0 || len(shape) == 0 {
		return errors.New("empty data or shape")
	}
	cs := C.CString(name)
	defer C.free(unsafe.Pointer(cs))
	rc := C.PD_SetInputInt64(p.c, cs, (*C.int64_t)(&data[0]),
		(*C.int64_t)(&shape[0]), C.int(len(shape)))
	runtime.KeepAlive(p)
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (p *Predictor) Run() error {
	rc := C.PD_Run(p.c)
	runtime.KeepAlive(p)
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (p *Predictor) OutputNum() int {
	n := int(C.PD_GetOutputNum(p.c))
	runtime.KeepAlive(p)
	return n
}

// OutputFloat copies output idx into a fresh slice plus its shape.
func (p *Predictor) OutputFloat(idx int) ([]float32, []int64, error) {
	var data *C.float
	var shape *C.int64_t
	var ndim C.int
	rc := C.PD_GetOutputFloat(p.c, C.int(idx), &data, &shape, &ndim)
	if rc != 0 {
		return nil, nil, lastError()
	}
	shp := make([]int64, int(ndim))
	n := int64(1)
	cshape := unsafe.Slice((*int64)(unsafe.Pointer(shape)), int(ndim))
	for i, d := range cshape {
		shp[i] = d
		n *= d
	}
	out := make([]float32, n)
	copy(out, unsafe.Slice((*float32)(unsafe.Pointer(data)), int(n)))
	runtime.KeepAlive(p)
	return out, shp, nil
}

// Trainer runs a saved (main, startup) training-program pair
// (reference fluid/train/demo/demo_trainer.cc; save the pair with
// paddle_tpu.static.save_train_program).
type Trainer struct {
	c *C.PD_Trainer
}

func NewTrainer(programDir string) (*Trainer, error) {
	cs := C.CString(programDir)
	defer C.free(unsafe.Pointer(cs))
	t := C.PD_NewTrainer(cs)
	if t == nil {
		return nil, lastError()
	}
	tr := &Trainer{c: t}
	runtime.SetFinalizer(tr, (*Trainer).finalize)
	return tr, nil
}

func (t *Trainer) finalize() { C.PD_DeleteTrainer(t.c) }

func (t *Trainer) SetInputFloat(name string, data []float32,
	shape []int64) error {
	if len(data) == 0 || len(shape) == 0 {
		return errors.New("empty data or shape")
	}
	cs := C.CString(name)
	defer C.free(unsafe.Pointer(cs))
	rc := C.PD_TrainerSetInputFloat(t.c, cs, (*C.float)(&data[0]),
		(*C.int64_t)(&shape[0]), C.int(len(shape)))
	runtime.KeepAlive(t)
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (t *Trainer) SetInputInt64(name string, data []int64,
	shape []int64) error {
	if len(data) == 0 || len(shape) == 0 {
		return errors.New("empty data or shape")
	}
	cs := C.CString(name)
	defer C.free(unsafe.Pointer(cs))
	rc := C.PD_TrainerSetInputInt64(t.c, cs, (*C.int64_t)(&data[0]),
		(*C.int64_t)(&shape[0]), C.int(len(shape)))
	runtime.KeepAlive(t)
	if rc != 0 {
		return lastError()
	}
	return nil
}

// Run performs one optimizer step and fetches fetchNames as float32.
// At least one fetch name is required (e.g. the loss variable).
func (t *Trainer) Run(fetchNames []string) error {
	if len(fetchNames) == 0 {
		return errors.New("Trainer.Run needs at least one fetch name")
	}
	cnames := make([]*C.char, len(fetchNames))
	for i, n := range fetchNames {
		cnames[i] = C.CString(n)
		defer C.free(unsafe.Pointer(cnames[i]))
	}
	rc := C.PD_TrainerRun(t.c, (**C.char)(&cnames[0]),
		C.int(len(cnames)))
	runtime.KeepAlive(t)
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (t *Trainer) FetchFloat(idx int) ([]float32, []int64, error) {
	var data *C.float
	var shape *C.int64_t
	var ndim C.int
	rc := C.PD_TrainerGetFetchFloat(t.c, C.int(idx), &data, &shape,
		&ndim)
	if rc != 0 {
		return nil, nil, lastError()
	}
	shp := make([]int64, int(ndim))
	n := int64(1)
	cshape := unsafe.Slice((*int64)(unsafe.Pointer(shape)), int(ndim))
	for i, d := range cshape {
		shp[i] = d
		n *= d
	}
	out := make([]float32, n)
	copy(out, unsafe.Slice((*float32)(unsafe.Pointer(data)), int(n)))
	runtime.KeepAlive(t)
	return out, shp, nil
}

// Save writes trained persistables (params + optimizer state).
func (t *Trainer) Save(dirname string) error {
	cs := C.CString(dirname)
	defer C.free(unsafe.Pointer(cs))
	rc := C.PD_TrainerSave(t.c, cs)
	runtime.KeepAlive(t)
	if rc != 0 {
		return lastError()
	}
	return nil
}
